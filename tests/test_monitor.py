"""SLO rules engine (obs/monitor.py): DSL parsing, threshold/rate/drift
evaluation, for=N streaks, latch-until-recovery, alert-record schema, and
the three actions (log / metric / preempt sentinel)."""

import json
import os

import pytest

from mpi_pytorch_tpu.obs.metrics import MetricsRegistry
from mpi_pytorch_tpu.obs.monitor import SLOMonitor, parse_rules
from mpi_pytorch_tpu.obs.schema import validate_record
from mpi_pytorch_tpu.utils.logging import MetricsWriter


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------


def test_parse_rule_full_form():
    (r,) = parse_rules(
        "serve/flush_ms:p99 > 250 for=3 warmup=7 name=serve_p99 "
        "severity=critical action=log,metric,preempt"
    )
    assert (r.name, r.metric, r.op, r.threshold) == (
        "serve_p99", "serve/flush_ms:p99", ">", 250.0,
    )
    assert (r.mode, r.for_count, r.warmup, r.severity) == ("value", 3, 7, "critical")
    assert r.actions == ("log", "metric", "preempt")


def test_parse_rule_modes_defaults_and_spacing():
    rules = parse_rules(
        "rate:serve/rejected>=5; drift:train/step_ms_last > 2.0;"
        "train/recompiles>0"
    )
    assert [r.mode for r in rules] == ["rate", "drift", "value"]
    assert [r.op for r in rules] == [">=", ">", ">"]
    assert rules[0].name == "serve/rejected"  # default name = metric
    assert rules[1].for_count == 1 and rules[1].warmup == 5
    assert rules[2].actions == ("log",)


@pytest.mark.parametrize(
    "bad",
    [
        "no_comparison_here",
        "m > notanumber",
        "m > 5 for=0",
        "m > 5 severity=panic",
        "m > 5 action=page",
        "m > 5 bogus=1",
        "> 5",
        "rate:idle < 1",  # below-rate rules page on silence: rejected
        "a > 1 name=x; b > 2 name=x",  # duplicate names
    ],
)
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_rules(bad)


def test_config_validates_rules_and_preempt_path(monkeypatch):
    from mpi_pytorch_tpu.config import Config

    cfg = Config(slo_rules="train/recompiles > 0", step_metrics=True)
    cfg.validate_config()
    cfg = Config(slo_rules="train/recompiles > zero", step_metrics=True)
    with pytest.raises(ValueError, match="not a number"):
        cfg.validate_config()
    # action=preempt needs a sentinel path the watchdog will poll.
    monkeypatch.delenv("MPT_PREEMPT_FILE", raising=False)
    cfg = Config(
        slo_rules="train/recompiles > 0 action=preempt", step_metrics=True
    )
    with pytest.raises(ValueError, match="preempt"):
        cfg.validate_config()
    cfg.preempt_file = "/tmp/x.sentinel"
    cfg.validate_config()


def test_config_rejects_rules_over_unpublished_metrics():
    """A rule whose source publisher is off would silently never evaluate
    — config rejects the combination loudly (the repo's silently-ignored-
    combination rule), naming the knob that arms the metric."""
    from mpi_pytorch_tpu.config import Config

    cfg = Config(slo_rules="train/recompiles > 0")  # step_metrics off
    with pytest.raises(ValueError, match="--step-metrics"):
        cfg.validate_config()
    cfg = Config(slo_rules="train/straggler_streak >= 3")  # heartbeat off
    with pytest.raises(ValueError, match="--heartbeat-every-steps"):
        cfg.validate_config()
    cfg = Config(
        slo_rules="train/straggler_streak >= 3", heartbeat_every_steps=4
    )
    cfg.validate_config()
    # Trainer-loop metrics the trainer itself publishes need no extra knob.
    Config(slo_rules="drift:train/step_ms_last > 2.0").validate_config()
    # scan_epoch has no per-step host boundaries to evaluate at.
    cfg = Config(
        slo_rules="drift:train/step_ms_last > 2.0",
        device_cache=True, scan_epoch=True,
    )
    with pytest.raises(ValueError, match="scan_epoch"):
        cfg.validate_config()


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _monitor(rules, tmp_path, registry=None, **kw):
    registry = registry or MetricsRegistry()
    writer = MetricsWriter(str(tmp_path / "m.jsonl"))
    mon = SLOMonitor(registry, parse_rules(rules), metrics=writer, **kw)
    return registry, writer, mon


def _records(tmp_path):
    path = tmp_path / "m.jsonl"
    if not path.exists():
        return []
    return [json.loads(line) for line in open(path) if line.strip()]


def test_threshold_rule_streak_latch_and_recovery(tmp_path):
    reg, writer, mon = _monitor("q > 10 for=2 name=deep", tmp_path)
    g = reg.gauge("q")
    g.set(50)
    assert mon.evaluate(epoch=0, step=0) == []  # streak 1 of 2
    assert mon.evaluate(epoch=0, step=1) == ["deep"]  # fires at streak 2
    assert mon.evaluate(epoch=0, step=2) == []  # latched: no alert spam
    g.set(3)
    assert mon.evaluate(epoch=0, step=3) == []  # recovery re-arms
    g.set(99)
    mon.evaluate(epoch=1, step=0)
    assert mon.evaluate(epoch=1, step=1) == ["deep"]  # fires again
    writer.close()

    alerts = [r for r in _records(tmp_path) if r["kind"] == "alert"]
    assert len(alerts) == 2
    a = alerts[0]
    assert validate_record(a) == []
    assert (a["rule"], a["severity"], a["value"], a["threshold"]) == (
        "deep", "warn", 50.0, 10.0,
    )
    assert (a["epoch"], a["step"], a["streak"]) == (0, 1, 2)


def test_unpublished_metric_never_fires(tmp_path):
    reg, writer, mon = _monitor("ghost:p99 > 1", tmp_path)
    for _ in range(5):
        assert mon.evaluate() == []
    writer.close()
    assert _records(tmp_path) == []


def test_histogram_quantile_rule(tmp_path):
    reg, writer, mon = _monitor("lat:p99 > 100 name=p99", tmp_path)
    h = reg.histogram("lat")
    for _ in range(99):
        h.observe(10.0)
    assert mon.evaluate() == []  # p99 of uniform 10s ≈ 10
    for _ in range(30):
        h.observe(5000.0)  # a latency cliff
    assert mon.evaluate() == ["p99"]
    writer.close()


def test_rate_rule_counts_deltas_per_second(tmp_path):
    t = [0.0]
    reg, writer, mon = _monitor(
        "rate:rejected > 5 name=reject_rate", tmp_path, clock=lambda: t[0],
    )
    c = reg.counter("rejected")
    assert mon.evaluate() == []  # no time elapsed since construction
    c.inc(2)
    t[0] = 1.0
    assert mon.evaluate() == []  # 2/s
    c.inc(50)
    t[0] = 2.0
    assert mon.evaluate() == ["reject_rate"]  # 50/s
    writer.close()
    (alert,) = [r for r in _records(tmp_path) if r["kind"] == "alert"]
    assert alert["metric"] == "rate:rejected"
    assert alert["value"] == pytest.approx(50.0)


def test_rate_rule_sees_burst_before_first_evaluation(tmp_path):
    """Rate rules baseline at CONSTRUCTION (counter = 0), so a burst that
    lands before the first evaluation counts as rate instead of vanishing
    into the baseline sample — the flood-of-rejects-while-the-first-flush-
    is-in-flight scenario, caught by a live flood drive."""
    t = [0.0]
    reg, writer, mon = _monitor(
        "rate:rejected > 5 name=reject_rate", tmp_path, clock=lambda: t[0],
    )
    reg.counter("rejected").inc(500)  # the pre-first-eval burst
    t[0] = 1.0
    assert mon.evaluate() == ["reject_rate"]  # 500/s, seen
    writer.close()


def test_drift_rule_builds_baseline_then_judges(tmp_path):
    reg, writer, mon = _monitor(
        "drift:step_ms > 2.0 warmup=3 name=drift", tmp_path
    )
    g = reg.gauge("step_ms")
    for v in (100.0, 110.0, 90.0):  # the baseline evals judge nothing
        g.set(v)
        assert mon.evaluate() == []
    g.set(150.0)  # 1.5x baseline(100): healthy
    assert mon.evaluate() == []
    g.set(330.0)  # 3.3x: drifted
    assert mon.evaluate() == ["drift"]
    writer.close()
    (alert,) = [r for r in _records(tmp_path) if r["kind"] == "alert"]
    assert alert["value"] == pytest.approx(3.3)
    assert alert["metric"] == "drift:step_ms"


def test_metric_action_counts_alerts(tmp_path):
    reg, writer, mon = _monitor("q > 1 action=metric name=a", tmp_path)
    reg.gauge("q").set(5)
    mon.evaluate()
    assert reg.snapshot()["counters"]["obs/alerts_fired"] == 1.0
    writer.close()


def test_preempt_action_writes_sentinel(tmp_path):
    sentinel = tmp_path / "deep" / "preempt.sentinel"
    reg, writer, mon = _monitor(
        "q > 1 action=preempt name=a", tmp_path, preempt_path=str(sentinel),
    )
    reg.gauge("q").set(5)
    assert mon.evaluate() == ["a"]
    assert sentinel.exists()
    body = sentinel.read_text()
    assert "slo:a" in body and "value=5" in body
    writer.close()


def test_preempt_action_without_path_warns_not_crashes(tmp_path, monkeypatch):
    monkeypatch.delenv("MPT_PREEMPT_FILE", raising=False)
    reg, writer, mon = _monitor("q > 1 action=preempt name=a", tmp_path)
    reg.gauge("q").set(5)
    assert mon.evaluate() == ["a"]  # alert recorded, preemption skipped
    writer.close()
    assert [r["kind"] for r in _records(tmp_path)] == ["alert"]


def test_monitor_env_sentinel_fallback(tmp_path, monkeypatch):
    sentinel = tmp_path / "env.sentinel"
    monkeypatch.setenv("MPT_PREEMPT_FILE", str(sentinel))
    reg, writer, mon = _monitor("q > 1 action=preempt name=a", tmp_path)
    assert mon.preempt_path == str(sentinel)
    reg.gauge("q").set(5)
    mon.evaluate()
    assert sentinel.exists()
    writer.close()
    assert os.path.exists(str(sentinel))
