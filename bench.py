"""Headline benchmark: resnet18 training throughput, images/sec/chip.

Mirrors the reference's north-star workload (``main.py``: resnet18, 64 500
classes, Adam 4e-4, 128×128 inputs) as one jitted DP train step over all
available chips, bfloat16 compute. Prints ONE JSON line:

    {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N, ...}

``vs_baseline`` is value ÷ the reference's best *per-worker* throughput
(≈4.4 img/s/worker — 800 imgs / 45.4 s over 4 MPI ranks, derived from
``training.log:1268-1275``; see BASELINE.md). ``mfu_pct`` is computed from
the XLA cost analysis of the compiled step against the chip's peak bf16
FLOP/s.

Timing notes: the state is donated through the step, so blocking on the
final state (not just a metrics scalar) is what guarantees every queued step
actually finished — scalar outputs can resolve early through the remote-PJRT
relay and overstate throughput by >5×.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

REFERENCE_IMG_PER_SEC_PER_WORKER = 4.4  # BASELINE.md, training.log:1268-1275

# TPU backend initialization (the first jax.devices() call) blocks
# INDEFINITELY when the device relay is wedged — observed live in this
# environment. The driver needs one JSON line either way, so a watchdog
# turns "hang forever" into a diagnosable failure. Disarmed once the
# backend is up; the benchmark itself is uninterrupted.
#
# A wedged init inside THIS process cannot be retried (the blocked RPC
# never returns and the TPU client is single-init), so the retry loop
# probes backend init in a CHILD interpreter first: each attempt gets an
# equal slice of the budget plus a short jittered backoff, and only after
# a probe succeeds does this process initialize (under the watchdog as
# the final backstop). Probes and the main init share ONE deadline, so
# the failure JSON always lands inside a single BACKEND_TIMEOUT_S window.
# A transient relay wedge — BENCH_r05 burned its whole 600 s window on
# one attempt, rc=3 — now costs one slice, not the window; CPU-pinned
# runs skip the probe (no relay to wedge). The healthy-relay cost of this
# insurance is ONE extra backend init per bench run (the probe child's),
# paid inside the same window — accepted deliberately: probe-first is the
# only retryable shape, because once THIS process's init wedges there is
# nothing left to retry.
try:
    BACKEND_TIMEOUT_S = int(os.environ.get("MPT_BENCH_BACKEND_TIMEOUT_S", "600"))
except ValueError:
    BACKEND_TIMEOUT_S = 600
if BACKEND_TIMEOUT_S <= 0:  # 0/negative would fire instantly, not disable
    BACKEND_TIMEOUT_S = 600
try:
    BACKEND_RETRIES = int(os.environ.get("MPT_BENCH_BACKEND_RETRIES", "3"))
except ValueError:
    BACKEND_RETRIES = 3
BACKEND_RETRIES = max(1, BACKEND_RETRIES)


# Probe attempts actually made before a failure, recorded by
# _probe_backend_with_retries so BOTH failure paths (probe exhaustion and
# the main-init watchdog) report it as a structured field — BENCH_r05's
# rc=3 row carried only prose, so flake frequency wasn't greppable across
# BENCH_r* artifacts.
_probe_attempts_made = 0


def _fail_json(error: str) -> None:
    print(
        json.dumps(
            {
                "metric": "resnet18 train images/sec/chip",
                "value": 0.0,
                "unit": "images/sec/chip",
                "vs_baseline": 0.0,
                "error": error,
                # Structured retry context for the BENCH_r* failure rows:
                # how many child probes ran (0 = CPU-pinned or the wedge hit
                # the main init before any probe) out of how many budgeted.
                "probe_attempts": _probe_attempts_made,
                "backend_retries": BACKEND_RETRIES,
                "backend_timeout_s": BACKEND_TIMEOUT_S,
            },
        ),
        flush=True,
    )


# ---------------------------------------------------------------------------
# Resumable partial bench rows (ROADMAP item 4's bench-resilience clause).
#
# A bench round through the device relay can die on ANY cell (r02 and r05
# both burned whole rounds on one wedged backend, rc=3). The fix is cell-
# granular durability: every completed row is appended to a
# ``BENCH_*.partial.json`` (cell key → row, atomic rename) the moment it
# lands, and ``--resume-from`` skips cells that file already holds — a
# retry re-measures only what the wedge ate. Shared by this headline bench
# and the tools/bench_modes.py sweep (which imports these helpers).
# ---------------------------------------------------------------------------


def load_partial(path: str) -> dict:
    """Rows already measured in a partial file ({cell key: row}). A missing,
    unreadable, or non-dict file is an empty dict — resume must never be
    the thing that wedges a retry."""
    if not path or not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            data = json.load(f)
    except (ValueError, OSError):
        return {}
    return data if isinstance(data, dict) else {}


def append_partial_row(path: str, key: str, row: dict) -> None:
    """Durably record one completed bench cell (read-modify-write, tmp +
    atomic rename): a backend wedge later in the round costs a retry of the
    REMAINING cells, not the whole round."""
    rows = load_partial(path)
    rows[key] = row
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rows, f, indent=1)
    os.replace(tmp, path)


def _probe_backend_with_retries(deadline: float) -> None:
    """Probe device-backend init in child interpreters, ``BACKEND_RETRIES``
    attempts with bounded jittered backoff inside the SHARED ``deadline``
    (the watchdog budget — probes and the main init together never exceed
    one ``BACKEND_TIMEOUT_S`` window, so the driver's failure JSON still
    arrives inside its documented window). Emits the failure JSON and
    exits 3 if no attempt succeeds.

    The probe is wedge insurance for the remote-PJRT relay; a CPU-pinned
    run (MPT_PLATFORM/JAX_PLATFORMS=cpu) cannot wedge this way and skips
    the extra child init entirely."""
    import random
    import subprocess
    import sys

    global _probe_attempts_made
    platform = (os.environ.get("MPT_PLATFORM")
                or os.environ.get("JAX_PLATFORMS") or "")
    if platform.split(",")[0].strip().lower() == "cpu":
        return
    per_attempt = max(30, BACKEND_TIMEOUT_S // (BACKEND_RETRIES + 1))
    errors = []
    for attempt in range(BACKEND_RETRIES):
        remaining = deadline - time.monotonic()
        # Leave at least one per-attempt slice of budget for the main
        # process's own init under the watchdog.
        if remaining <= per_attempt:
            break
        _probe_attempts_made = attempt + 1
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True,
                text=True,
                timeout=min(per_attempt, remaining - per_attempt),
            )
            if proc.returncode == 0:
                return
            tail = (proc.stderr or "").strip().splitlines()[-1:]
            errors.append(f"attempt {attempt + 1}: rc={proc.returncode} "
                          + " ".join(tail)[:120])
        except subprocess.TimeoutExpired:
            errors.append(f"attempt {attempt + 1}: no init within "
                          f"{per_attempt:.0f}s")
        if attempt < BACKEND_RETRIES - 1 and time.monotonic() < deadline:
            # Jittered backoff: desynchronizes retries from a recovering
            # relay (and from sibling benches a battery may have spawned).
            time.sleep(min(random.uniform(1, 5) * (attempt + 1),
                           max(0.0, deadline - time.monotonic())))
    if errors:
        _fail_json(
            f"device backend failed to initialize within {BACKEND_TIMEOUT_S}s "
            f"({len(errors)} probe attempts; wedged TPU relay?): "
            + " | ".join(errors[-3:])
        )
        os._exit(3)


def _arm_backend_watchdog(deadline: float) -> threading.Event:
    armed = threading.Event()

    def fire() -> None:
        if armed.wait(max(1.0, deadline - time.monotonic())):
            return
        _fail_json(
            f"device backend failed to initialize within "
            f"{BACKEND_TIMEOUT_S}s (wedged TPU relay?)"
        )
        os._exit(3)

    threading.Thread(target=fire, daemon=True).start()
    return armed

MODEL = "resnet18"
NUM_CLASSES = 64500   # utils.py:39
IMAGE = 128           # utils.py:33-34
BATCH_PER_CHIP = 2048  # throughput-optimal on v5e. B-sweep with the bf16
#                        head (models/resnet.py): 21.5k img/s @512, 22.3k
#                        @1024, 23.2k @2048 (38.5% MFU) — larger batches
#                        amortize the bandwidth-bound backbone better.
WARMUP_STEPS = 5
MEASURE_STEPS = 30

def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="headline resnet18 train bench (one JSON line)"
    )
    ap.add_argument(
        "--partial-out", default=os.environ.get("MPT_BENCH_PARTIAL", ""),
        help="also append the completed row to this BENCH_*.partial.json "
             "the moment it lands (cell-granular durability)",
    )
    ap.add_argument(
        "--resume-from", default="",
        help="if this partial file already holds the cell, reprint the "
             "stored row and exit without touching the backend",
    )
    args = ap.parse_args(argv)
    cell = f"{MODEL}-b{BATCH_PER_CHIP}"
    resumed = load_partial(args.resume_from).get(cell)
    if resumed is not None:
        # The whole point of resume: a retry after a wedge never re-enters
        # backend init for cells that already landed.
        print(json.dumps(resumed), flush=True)
        return

    # ONE shared budget: child probes (bounded jittered retries) + the main
    # process's own init under the watchdog together fit the window, so the
    # driver's failure JSON always lands inside BACKEND_TIMEOUT_S.
    deadline = time.monotonic() + BACKEND_TIMEOUT_S
    _probe_backend_with_retries(deadline)
    backend_up = _arm_backend_watchdog(deadline)
    import jax
    import jax.numpy as jnp

    jax.devices()  # force backend init under the watchdog
    backend_up.set()

    from mpi_pytorch_tpu.config import enable_compilation_cache

    # MPT_COMPILE_CACHE_DIR: persistent compilation cache, so a repeat bench
    # (same shapes, same options) skips its cold compile entirely — through
    # the relay that compile IS most of a bench run's wall time.
    enable_compilation_cache()

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.obs import Tracer
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh
    from mpi_pytorch_tpu.utils.hardware import peak_bf16_tflops, step_flops

    # MPT_TRACE_FILE=path → host-side Chrome-trace spans for the bench's
    # phases (compile/warmup/measure — obs/trace.py), so a slow bench run
    # through the relay is attributable without re-running under a profiler.
    tracer = Tracer(os.environ.get("MPT_TRACE_FILE", ""))

    n_chips = jax.device_count()
    batch = BATCH_PER_CHIP * n_chips

    mesh = create_mesh(Config().mesh)
    # Fused bn1+relu+maxpool stem (ops/fused_stem.py): the headline winner
    # on chip (docs/RESULTS.md §4d). MPT_FUSED_STEM=0 reverts to the
    # unfused XLA stem for A/B.
    from mpi_pytorch_tpu.models.registry import fused_stem_default

    _fused = fused_stem_default(MODEL)
    bundle, variables = create_model_bundle(
        MODEL, NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=IMAGE,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
        fused_stem=_fused,
        # Multi-chip: the stem kernel shard_maps itself over the data axis
        # (ops/fused_stem.py, Multi-chip).
        dp_mesh=mesh if _fused else None,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(4e-4), rng=jax.random.PRNGKey(1),
    )
    state = place_state_on_mesh(state, mesh)
    step = make_train_step(jnp.bfloat16)

    rng = np.random.default_rng(0)
    images = rng.standard_normal((batch, IMAGE, IMAGE, 3), np.float32)
    labels = rng.integers(0, NUM_CLASSES, size=(batch,)).astype(np.int32)
    device_batch = shard_batch((images, labels), mesh)

    # TPU compiler options. Default: 64 MiB scoped VMEM, the measured
    # winner of the tools/bench_flags.py sweep on this workload
    # (docs/flags_vmem_sweep.json: 25.3k img/s / 41.9% MFU vs 24.1k / 40.0%
    # baseline; 48/80/96/128 MiB all inferior). A set MPT_COMPILER_OPTIONS
    # (JSON dict) REPLACES the default entirely — so bench_flags.py's
    # baseline="{}" row really is the no-options baseline — and must hold
    # PER-COMPILE options, not XLA_FLAGS: the relay's client-side XLA
    # fatally rejects TPU-only flags it doesn't know (the TPU compiler
    # lives server-side).
    env_options = os.environ.get("MPT_COMPILER_OPTIONS")
    if env_options is not None:
        options = json.loads(env_options)
    elif jax.devices()[0].platform == "tpu":
        options = {"xla_tpu_scoped_vmem_limit_kib": 65536}
    else:
        options = {}
    # finally-close: a wedged/aborted bench is exactly the run whose trace
    # is needed to see which phase it died in.
    try:
        with tracer.span("compile"):
            compiled = step.lower(state, device_batch).compile(
                compiler_options=options or None
            )
        flops_per_step = step_flops(compiled)

        with tracer.span("warmup", args={"steps": WARMUP_STEPS}):
            for _ in range(WARMUP_STEPS):
                state, metrics = compiled(state, device_batch)
            jax.block_until_ready(state.params)

        t0 = time.perf_counter()
        with tracer.span("measure", args={"steps": MEASURE_STEPS}):
            for _ in range(MEASURE_STEPS):
                state, metrics = compiled(state, device_batch)
            jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
    finally:
        tracer.close()

    ips = MEASURE_STEPS * batch / dt
    # cost_analysis() FLOPs are PER-DEVICE under SPMD partitioning, so this
    # is already per-chip achieved TFLOP/s — no further division by n_chips.
    tflops_per_chip = flops_per_step * MEASURE_STEPS / dt / 1e12
    peak = peak_bf16_tflops(jax.devices()[0])
    record = {
        "metric": (
            f"{MODEL} train images/sec/chip (bf16, {NUM_CLASSES} classes, "
            f"{IMAGE}px, batch {BATCH_PER_CHIP}/chip, {n_chips} chip(s))"
        ),
        "value": round(ips / n_chips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips / n_chips / REFERENCE_IMG_PER_SEC_PER_WORKER, 2),
        "tflops_per_chip": round(tflops_per_chip, 2),
    }
    if peak and flops_per_step > 0:
        record["mfu_pct"] = round(100.0 * tflops_per_chip / peak, 1)
    print(json.dumps(record))
    if args.partial_out:
        append_partial_row(args.partial_out, cell, record)


if __name__ == "__main__":
    main()
