"""Child process for the two-process ``jax.distributed`` smoke test.

Each of the 2 processes owns 4 virtual CPU devices (8 global). The parent
sets JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID and
MPT_MULTIHOST=1; this script goes through the framework's real multi-host
path: ``maybe_initialize_distributed`` → per-host manifest-style batch →
``shard_batch`` (which takes the ``make_array_from_process_local_data``
branch when process_count > 1) → one DP train step with a cross-process
gradient all-reduce over gloo CPU collectives.

Prints ``DIST_OK <loss:.6f>`` on success; the parent asserts both processes
print the same loss (the all-reduce made them agree).
"""

import sys

import jax

jax.config.update("jax_platforms", "cpu")  # before first device use

import numpy as np  # noqa: E402

sys.path.insert(0, ".")

from mpi_pytorch_tpu.parallel.distributed import maybe_initialize_distributed  # noqa: E402


def main() -> None:
    assert maybe_initialize_distributed(), "distributed init did not trigger"
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.parallel.mesh import create_mesh, shard_batch
    from mpi_pytorch_tpu.config import MeshConfig
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step, place_state_on_mesh

    mesh = create_mesh(MeshConfig())
    bundle, variables = create_model_bundle(
        "resnet18", 16, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
    )
    state = place_state_on_mesh(state, mesh)

    # Per-host shard of the global batch: DIFFERENT data on each process
    # (seeded by process index), so agreement on the loss below proves the
    # cross-process collective actually reduced over both hosts' shards.
    rng = np.random.default_rng(jax.process_index())
    host_images = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    host_labels = (np.arange(8, dtype=np.int32) + 8 * jax.process_index()) % 16

    step = make_train_step(jax.numpy.float32)
    batch = shard_batch((host_images, host_labels), mesh)
    state, metrics = step(state, batch)
    jax.block_until_ready(state.params)
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss
    print(f"DIST_OK {loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
