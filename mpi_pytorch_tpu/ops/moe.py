"""Mixture-of-Experts FFN with expert parallelism (EP) over a mesh axis.

The reference has no MoE (its seven CNNs are dense, ``models.py:16-101``;
SURVEY §2c lists EP as absent), but a complete TPU-native parallelism matrix
needs the strategy: experts are sharded over an ``expert`` mesh axis and
tokens travel to their experts over the ICI via ``lax.all_to_all`` — the
canonical TPU MoE dataflow (dispatch → all-to-all → local expert FFNs →
all-to-all back → combine).

Routing is Mesh-TensorFlow-style static-capacity top-k:

- gate logits over all ``E`` experts, softmax, top-k choice per token;
- each expert accepts at most ``capacity`` tokens *per shard* (XLA needs
  static shapes — overflow tokens are dropped from that expert's
  contribution, exactly like production TPU MoEs; their combine weight is 0
  so the token simply passes less signal through);
- dispatch/combine are one-hot tensors ``[T, E, C]``, so dispatch is an
  einsum (MXU work, not scatter).

The auxiliary load-balance loss (Shazeer et al.: ``E · Σ_e f_e · p̄_e``)
is returned alongside the output; add it to the task loss with a small
coefficient to keep routing uniform.

tests/test_moe.py asserts the 8-shard EP result equals a dense single-device
evaluation of the same routing, values and gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_pytorch_tpu.parallel.compat import shard_map


def init_moe_params(rng, d_model: int, d_hidden: int, num_experts: int) -> dict:
    """Gate + per-expert two-layer FFN params. Expert-axis-leading leaves
    (``w1 [E, d, h]`` etc.) so EP sharding is a leading-axis PartitionSpec."""
    kg, k1, k2 = jax.random.split(rng, 3)
    scale1 = (2.0 / d_model) ** 0.5
    scale2 = (2.0 / d_hidden) ** 0.5
    return {
        "gate": jax.random.normal(kg, (d_model, num_experts), jnp.float32)
        * (1.0 / d_model**0.5),
        "w1": jax.random.normal(k1, (num_experts, d_model, d_hidden), jnp.float32)
        * scale1,
        "b1": jnp.zeros((num_experts, d_hidden), jnp.float32),
        "w2": jax.random.normal(k2, (num_experts, d_hidden, d_model), jnp.float32)
        * scale2,
        "b2": jnp.zeros((num_experts, d_model), jnp.float32),
    }


def _routing(gate_logits, k: int, capacity: int):
    """Top-k static-capacity routing → (dispatch [T,E,C], combine [T,E,C],
    aux load-balance loss). Pure function of the gate logits; shared by the
    EP path and the dense reference so the two can never disagree."""
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Fill per-expert capacity slots choice-by-choice: the j-th choices of
    # all tokens are assigned after every (j-1)-th choice, tokens in order —
    # a deterministic, priority-respecting slotting (standard MTF semantics).
    taken = jnp.zeros((e,), jnp.int32)  # slots already used per expert
    masked = probs
    for _ in range(k):
        choice = jnp.argmax(masked, axis=-1)  # [T]
        gatew = jnp.take_along_axis(probs, choice[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [T, E]
        # Position of each token within its chosen expert's buffer.
        pos = taken[choice] + (jnp.cumsum(onehot, axis=0) - onehot)[
            jnp.arange(t), choice
        ]
        keep = pos < capacity
        oh = (
            jax.nn.one_hot(choice, e, dtype=jnp.float32)[:, :, None]
            * jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity)[:, None, :]
            * keep[:, None, None]
        )
        dispatch = dispatch + oh
        combine = combine + oh * gatew[:, None, None]
        taken = taken + jnp.sum(onehot, axis=0)
        masked = jnp.where(jax.nn.one_hot(choice, e, dtype=bool), -jnp.inf, masked)

    # Load-balance aux (Shazeer): fraction of token-routings landing on e
    # (all k choices, normalized by k) × mean gate prob for e, summed, ×E.
    frac = jnp.mean(dispatch.sum(-1), axis=0)  # [E] tokens-per-expert / T
    aux = e * jnp.sum(frac / max(k, 1) * jnp.mean(probs, axis=0))
    return dispatch, combine, aux


def pick_group_size(tokens: int, group_size: int | None) -> int:
    """Largest divisor of ``tokens`` that is <= ``group_size`` (all-tokens
    when None). Grouped routing needs the token count to split into equal
    groups; blind clamping to min(group_size, tokens) crashes on token
    counts that are not multiples of the requested group."""
    if group_size is None or group_size >= tokens:
        return tokens
    g = max(1, group_size)
    while tokens % g:
        g -= 1
    return g


def _grouped_routing(gate_logits, k: int, capacity: int, group_size: int):
    """Group-wise routing: tokens are routed in independent groups of
    ``group_size``, each with its own ``capacity`` slots per expert. This is
    what makes the one-hot dispatch scale: per-group dispatch is [g, E, C]
    with C ∝ g, so the total [G, g, E, C] tensor is LINEAR in token count
    (ungrouped [T, E, C] with C ∝ T is quadratic — unusable at training
    batch sizes). Returns dispatch/combine [G, g, E, C] and the aux loss
    averaged over groups."""
    t = gate_logits.shape[0]
    if t % group_size:
        raise ValueError(f"tokens {t} not divisible by group_size {group_size}")
    grouped = gate_logits.reshape(t // group_size, group_size, -1)
    dispatch, combine, aux = jax.vmap(
        lambda gl: _routing(gl, k, capacity)
    )(grouped)
    return dispatch, combine, jnp.mean(aux)


def dense_moe(
    params: dict,
    x,
    *,
    k: int = 2,
    capacity: int | None = None,
    group_size: int | None = None,
):
    """Single-device reference MoE (also the EP-free fallback): same routing,
    experts applied by einsum over the full expert axis. Returns (y, aux).

    ``group_size`` routes tokens in independent fixed-size groups; capacity
    is then PER GROUP. Defaults: one group of all tokens, capacity =
    group size (no drops). See ``_grouped_routing`` for why grouping is the
    scalable form."""
    t, d = x.shape
    g = group_size if group_size is not None else t
    capacity = capacity if capacity is not None else g
    dispatch, combine, aux = _grouped_routing(x @ params["gate"], k, capacity, g)
    xg = x.reshape(t // g, g, d)
    xin = jnp.einsum("gtec,gtd->gecd", dispatch, xg)
    h = jax.nn.gelu(
        jnp.einsum("gecd,edh->gech", xin, params["w1"]) + params["b1"][None, :, None]
    )
    out = jnp.einsum("gech,ehd->gecd", h, params["w2"]) + params["b2"][None, :, None]
    y = jnp.einsum("gecd,gtec->gtd", out, combine)
    return y.reshape(t, d).astype(x.dtype), aux


def moe_ffn(
    params: dict,
    x,
    *,
    axis_name: str,
    k: int = 2,
    capacity: int,
    group_size: int | None = None,
):
    """Per-shard expert-parallel MoE. Must run inside an SPMD context binding
    ``axis_name`` (size n): ``x [t_local, d]`` is the shard's tokens;
    ``params['w1']/['b1']/['w2']/['b2']`` hold only the shard's ``E/n`` local
    experts (leading axis sharded); ``params['gate']`` is replicated.

    Dataflow per shard: route against ALL ``E`` experts (group-wise, capacity
    per group — see ``_grouped_routing``) → buffers ``[E, G·C, d]`` → tiled
    ``all_to_all`` regroups to ``[E/n, n·G·C, d]`` (my experts, every shard's
    slots) → local expert FFNs → inverse ``all_to_all`` → weighted combine.
    Returns ``(y [t_local, d], aux)`` with ``aux`` pmean'd across shards.
    """
    t, d = x.shape
    e = params["gate"].shape[1]
    g = group_size if group_size is not None else t
    dispatch, combine, aux = _grouped_routing(x @ params["gate"], k, capacity, g)

    xg = x.reshape(t // g, g, d)
    xin = jnp.einsum("gtec,gtd->gecd", dispatch, xg)  # [G, E, C, d]
    # Fold groups into the slot axis so the all_to_all sees one [E, G*C, d]
    # buffer (expert compute is position-agnostic along slots).
    n_groups, _, cap = xin.shape[0], xin.shape[1], xin.shape[2]
    xin = xin.transpose(1, 0, 2, 3).reshape(e, n_groups * cap, d)
    # → [E/n, n*G*C, d]: shard i keeps rows for ITS experts from every shard.
    xin = lax.all_to_all(xin, axis_name, split_axis=0, concat_axis=1, tiled=True)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edh->ech", xin, params["w1"]) + params["b1"][:, None]
    )
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"]) + params["b2"][:, None]
    # Inverse regroup: back to [E, G*C, d] rows for MY tokens.
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0, tiled=True)
    out = out.reshape(e, n_groups, cap, d).transpose(1, 0, 2, 3)
    y = jnp.einsum("gecd,gtec->gtd", out, combine).reshape(t, d).astype(x.dtype)
    return y, lax.pmean(aux, axis_name)


@functools.lru_cache(maxsize=None)
def _moe_jit(mesh, axis, k, capacity, group_size):
    pspec = {
        "gate": P(),
        "w1": P(axis),
        "b1": P(axis),
        "w2": P(axis),
        "b2": P(axis),
    }
    fn = shard_map(
        functools.partial(
            moe_ffn, axis_name=axis, k=k, capacity=capacity, group_size=group_size
        ),
        mesh=mesh,
        in_specs=(pspec, P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def moe_forward(
    params: dict,
    x,
    mesh: Mesh,
    *,
    expert_axis: str | None = None,
    k: int = 2,
    capacity: int | None = None,
    group_size: int | None = None,
):
    """Driver-facing wrapper: tokens ``[T, d]`` sharded over ``expert_axis``
    (EP=DP layout — each shard routes its own tokens), experts sharded over
    the same axis. ``group_size`` (clamped to the per-shard token count)
    routes in independent groups; ``capacity`` is PER GROUP and defaults to
    the group size (no drops when routing is balanced within 1×). Returns
    ``(y [T, d], aux_loss)``."""
    expert_axis = expert_axis or mesh.axis_names[0]
    n = mesh.shape[expert_axis]
    t = x.shape[0]
    e = params["gate"].shape[1]
    if t % n or e % n:
        raise ValueError(
            f"'{expert_axis}' axis size {n} must divide both "
            f"tokens ({t}) and experts ({e})"
        )
    g = pick_group_size(t // n, group_size)
    capacity = capacity if capacity is not None else g
    return _moe_jit(mesh, expert_axis, k, capacity, g)(params, x)
