"""Host-side input pipeline: decode → RGB → resize → normalize → batch → prefetch.

This collapses two reference components into one idiomatic pipeline:

- ``data_loader.py:6-39`` (``GetData`` Dataset: per-item PIL open + transform)
- the first three stages of the 4-stage MPI inference pipeline
  (``evaluation_pipeline.py:53-129``: rank 0 reads, rank 1 resizes, rank 2
  normalizes, streaming pickled PIL images between ranks over MPI send/recv).

TPU-first design: the pipeline overlap the MPI stages bought with dedicated
ranks is had for free with a thread pool + a bounded prefetch queue on each
host; the device only ever sees fixed-shape normalized float batches, so the
jitted step never recompiles. Transform math matches the reference
(``main.py:62-65``): ToTensor (scale to [0,1]) → Resize(H,W) → Normalize
(ImageNet mean/std), with the grayscale fix (`.convert('RGB')`) the reference
is missing (SURVEY §3 quirks).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator

import numpy as np

from mpi_pytorch_tpu.config import IMAGENET_MEAN, IMAGENET_STD
from mpi_pytorch_tpu.data.manifest import Manifest
from mpi_pytorch_tpu.utils.env import fault_countdown


class BadSampleLimitError(RuntimeError):
    """More samples failed to decode than ``max_bad_samples`` tolerates.
    Raised AFTER the final sample was quarantined and recorded, so the
    abort carries a full quarantine trail — a dataset rotting past the
    budget must fail the run loudly, not train on substitute rows."""

_MEAN = np.asarray(IMAGENET_MEAN, dtype=np.float32)
_STD = np.asarray(IMAGENET_STD, dtype=np.float32)

# Normalized synthetic images by (label, size), capped by BYTES so image
# size doesn't change the memory footprint. First-come insertion: covers
# small-vocabulary runs (e.g. the DEBUG sample's 964 classes) completely;
# full-64500-class runs fall back to regeneration for uncached labels.
_SYNTH_CACHE: dict = {}
_SYNTH_CACHE_BUDGET = 256 * 1024 * 1024
_synth_cache_bytes = 0
# Guards the check-then-insert (loader worker threads share the cache); the
# lock-free read in _load_one is safe under the GIL.
_SYNTH_CACHE_LOCK = threading.Lock()


def epoch_order(seed: int, epoch: int, n: int, shuffle: bool) -> np.ndarray:
    """THE per-epoch visit order, shared by the streaming loader and the
    device-cache index path so both walk the data identically: deterministic
    per ``(seed, epoch)`` — the shuffle discipline the reference lacks
    (``main.py:102``; SURVEY §3 quirks)."""
    if shuffle:
        return np.random.default_rng((seed, epoch)).permutation(n)
    return np.arange(n)


def normalize_image(img: np.ndarray) -> np.ndarray:
    """[0,1] float32 HWC → ImageNet-normalized (parity: transforms.Normalize,
    ``main.py:65``)."""
    return (img - _MEAN) / _STD


def decode_image(path: str, image_size: tuple[int, int]) -> np.ndarray:
    """PIL decode → RGB → resize → [0,1] float32 HWC.

    Matches the reference transform order ToTensor→Resize (``main.py:62-64``)
    numerically: PIL bilinear on the uint8 image differs from torch's resize
    of the float tensor only by rounding; both produce [0,1] floats at (H,W).
    """
    from PIL import Image

    with Image.open(path) as im:
        im = im.convert("RGB").resize((image_size[1], image_size[0]), Image.BILINEAR)
        return np.asarray(im, dtype=np.float32) / 255.0


def synthetic_image(seed: int, image_size: tuple[int, int]) -> np.ndarray:
    """Deterministic synthetic image for environments without the Herbarium
    images (they are gitignored in the reference too, ``.gitignore:2-4``).

    Class-conditioned structure (low-frequency pattern keyed by the seed) so a
    model can actually learn from synthetic data in integration tests.
    """
    rng = np.random.default_rng(seed)
    h, w = image_size
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    freq = rng.uniform(0.02, 0.3, size=(3,))
    phase = rng.uniform(0, 2 * np.pi, size=(3,))
    img = 0.5 + 0.5 * np.sin(freq[None, None, :] * (yy + xx)[:, :, None] + phase[None, None, :])
    noise = rng.normal(0, 0.05, size=(h, w, 3)).astype(np.float32)
    return np.clip(img + noise, 0.0, 1.0).astype(np.float32)


class DataLoader:
    """Sharded, shuffled, prefetching batch loader.

    Parity mapping:
    - shard-per-process       ≙ rank-0 scatter (``main.py:84-91``)
    - seeded epoch shuffle    ≙ DataLoader(shuffle=True) (``main.py:102``) but
      deterministic per (seed, epoch) — a discipline the reference lacks
      (SURVEY §3 quirks).
    - worker thread pool      ≙ per-item loading inside torch DataLoader
    - prefetch queue          ≙ the overlap the MPI pipeline stages provided
    Batches are (images [B,H,W,3] normalized in ``image_dtype`` — float32 by
    default, bfloat16 to halve host→device transfer — labels [B] int32).
    """

    def __init__(
        self,
        manifest: Manifest,
        batch_size: int,
        image_size: tuple[int, int],
        *,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        synthetic: bool = False,
        num_workers: int = 8,
        prefetch: int = 2,
        image_dtype: str = "float32",
        native_decode: bool = True,
        decode_prescale: int = 2,
        host_cache: bool = False,
        packed_dir: str = "",
        max_bad_samples: int = 16,
        quarantine_file: str = "",
        decode_retries: int = 2,
        decode_retry_backoff_s: float = 0.05,
    ):
        self.manifest = manifest
        self.batch_size = batch_size
        self.image_size = image_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder
        self.synthetic = synthetic
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.decode_prescale = decode_prescale
        # Decode-failure robustness: a sample that still fails after
        # ``decode_retries`` bounded-backoff retries is QUARANTINED — its
        # batch row becomes a copy of a good row with label -1 (masked by
        # the loss exactly like padding), its path is appended to
        # ``quarantine_file`` ("" = no file) and a kind="anomaly"
        # reason="bad_sample" record is written when a metrics writer is
        # attached (``self.metrics``, set by the trainer). More than
        # ``max_bad_samples`` quarantines abort the run loudly
        # (BadSampleLimitError).
        self.max_bad_samples = max_bad_samples
        self.quarantine_file = quarantine_file
        self.decode_retries = max(0, decode_retries)
        self.decode_retry_backoff_s = decode_retry_backoff_s
        self.metrics = None  # optional MetricsWriter, attached post-build
        self.bad_samples = 0
        self._quarantined: set[int] = set()  # manifest row indices
        self._poisoned_decode: set[int] = set()  # MPT_FAULT_DECODE_N victims
        self._bad_lock = threading.Lock()
        self._cur_epoch = 0
        # Decode the whole shard ONCE into host RAM (first epoch), then serve
        # every later epoch by slicing — zero decode cost after epoch 0, at
        # the price of n_images × H × W × 3 × dtype host memory. Works
        # per-host (multi-host safe) and for datasets bigger than HBM —
        # the middle ground between streaming and the device cache.
        self.host_cache = host_cache
        self._cache_images: np.ndarray | None = None
        self._cache_filled: np.ndarray | None = None  # [n] bool, rows decoded
        self._cache_complete = False
        self._fill_thread: threading.Thread | None = None  # in-flight filler
        self._cache_fill_error: BaseException | None = None  # undelivered
        # Offline-packed uint8 dataset (data/packed.py): batches become mmap
        # row slices + a vectorized normalize — no decode at run time at all.
        # Resolution is strict: a set packed_dir with no covering pack raises.
        self.packed_dir = packed_dir
        self._pack = None
        if packed_dir:
            from mpi_pytorch_tpu.data.packed import find_pack

            self._pack = find_pack(packed_dir, manifest, image_size, synthetic)
        # image_dtype 'uint8' = RAW-pixel batches (train/step.py ingest_images
        # normalizes on device): 4x less H2D than f32, 4x smaller host cache;
        # packed batches become plain mmap slices with no host float work.
        self.raw_uint8 = image_dtype == "uint8"
        # Native C++ batched ingest (mpi_pytorch_tpu/native): one GIL-released
        # call decodes the whole batch on C threads. Auto-falls back to the
        # PIL thread pool when the toolchain/libjpeg is unavailable. (Its
        # fused output is normalized f32, so raw-uint8 mode uses PIL.)
        self.native_decode = False
        if native_decode and not synthetic and self._pack is None and not self.raw_uint8:
            from mpi_pytorch_tpu import native as _native

            self.native_decode = _native.available()
        # bfloat16 batches halve host→device transfer (the step computes in
        # bf16 anyway); decode/normalize still run in float32 on the host.
        if image_dtype == "bfloat16":
            import ml_dtypes

            self.image_dtype = np.dtype(ml_dtypes.bfloat16)
        else:
            self.image_dtype = np.dtype(image_dtype)

    def __len__(self) -> int:
        n = len(self.manifest)
        return n // self.batch_size if self.drop_remainder else -(-n // self.batch_size)

    def _sample_name(self, i: int) -> str:
        if self.synthetic:
            return f"synthetic:{int(self.manifest.labels[i])}@{i}"
        return os.path.join(self.manifest.img_dir, self.manifest.filenames[i])

    def _quarantine(self, i: int, err: BaseException) -> None:
        """Record one undecodable sample: remember its row (labels mask to
        -1 from now on, including cached epochs), log it, append the path to
        the quarantine file, write the anomaly record — then abort loudly
        once the budget is blown. Runs on worker threads."""
        from mpi_pytorch_tpu.utils.logging import run_logger

        name = self._sample_name(i)
        with self._bad_lock:
            already = i in self._quarantined
            self._quarantined.add(i)
            if not already:
                self.bad_samples += 1
            count = self.bad_samples
        if already:
            return
        run_logger().warning(
            "quarantined undecodable sample %s (%d/%d bad allowed): %s",
            name, count, self.max_bad_samples, err,
        )
        if self.quarantine_file:
            with self._bad_lock:
                with open(self.quarantine_file, "a") as f:
                    f.write(f"{name}\t{type(err).__name__}: {err}\n")
        if self.metrics is not None:
            self.metrics.write(
                {
                    "kind": "anomaly", "reason": "bad_sample",
                    "epoch": self._cur_epoch, "path": name,
                    "detail": f"{type(err).__name__}: {err}",
                }
            )
        if count > self.max_bad_samples:
            raise BadSampleLimitError(
                f"{count} undecodable samples exceed max_bad_samples="
                f"{self.max_bad_samples} (latest: {name}: {err}); see the "
                f"quarantine trail"
            ) from err

    def _decode_with_retries(self, i: int) -> np.ndarray | None:
        """``_load_one`` behind bounded-backoff retries; None = quarantined
        (the caller substitutes a good row and masks the label)."""
        delay = self.decode_retry_backoff_s
        err: BaseException | None = None
        for attempt in range(self.decode_retries + 1):
            try:
                return self._load_one(i)
            except BadSampleLimitError:
                raise
            except Exception as e:
                err = e
                if attempt < self.decode_retries and delay > 0:
                    time.sleep(delay)
                    delay *= 2
        self._quarantine(i, err)
        return None

    def _masked_labels(self, idx: np.ndarray) -> np.ndarray:
        """Batch labels with quarantined rows masked to -1 (the padding
        label the loss already ignores) — THE label source of every batch
        path, so a row quarantined in epoch 0 stays masked when later
        epochs serve it from the host cache."""
        labels = np.asarray(self.manifest.labels[idx])
        if self._quarantined:
            bad = np.fromiter(
                (int(j) in self._quarantined for j in idx), bool, len(idx)
            )
            if bad.any():
                labels = np.where(bad, np.int32(-1), labels).astype(labels.dtype)
        return labels

    def _load_one(self, i: int) -> np.ndarray:
        # MPT_FAULT_DECODE_N poisons N DISTINCT samples permanently (one
        # countdown shot per sample on first draw, then every retry of that
        # sample fails too) — deterministic regardless of worker-thread
        # interleaving, so N=1 always quarantines exactly one sample.
        if int(i) in self._poisoned_decode:
            raise RuntimeError(
                f"injected decode failure (MPT_FAULT_DECODE_N) for "
                f"{self._sample_name(i)}"
            )
        if fault_countdown("MPT_FAULT_DECODE_N"):
            self._poisoned_decode.add(int(i))
            raise RuntimeError(
                f"injected decode failure (MPT_FAULT_DECODE_N) for "
                f"{self._sample_name(i)}"
            )
        if self.synthetic:
            # Key the pattern by label so classes are separable. The pattern
            # is a pure function of (label, size, dtype), so a bounded cache
            # removes the host-side generation bottleneck (1 CPU core feeding
            # a TPU). raw-uint8 mode caches the quantized pixels instead.
            key = (int(self.manifest.labels[i]), self.image_size, self.raw_uint8)
            img = _SYNTH_CACHE.get(key)
            if img is None:
                global _synth_cache_bytes
                if self.raw_uint8:
                    from mpi_pytorch_tpu.data.packed import _synthetic_uint8

                    img = _synthetic_uint8(key[0], self.image_size)
                else:
                    img = normalize_image(synthetic_image(key[0], self.image_size))
                with _SYNTH_CACHE_LOCK:
                    if key not in _SYNTH_CACHE and (
                        _synth_cache_bytes + img.nbytes <= _SYNTH_CACHE_BUDGET
                    ):
                        _SYNTH_CACHE[key] = img
                        _synth_cache_bytes += img.nbytes
            return img
        path = os.path.join(self.manifest.img_dir, self.manifest.filenames[i])
        if self.raw_uint8:
            # Shared with the pack writer — the single point of truth that
            # keeps pack ≡ streaming bit-identity for raw-uint8 batches.
            from mpi_pytorch_tpu.data.packed import _decode_uint8

            return _decode_uint8(path, self.image_size)
        return normalize_image(decode_image(path, self.image_size))

    def _load_batch(self, idx: np.ndarray, pool: ThreadPoolExecutor) -> np.ndarray:
        """Load a batch of images [B,H,W,3]: normalized f32, or RAW uint8
        pixels in ``raw_uint8`` mode (normalization then happens on device,
        train/step.py ``ingest_images``). Sources in order: packed mmap rows
        when a pack is resolved, else one GIL-released native call when
        available, else the PIL thread pool."""
        if self._pack is not None:
            if self.raw_uint8:
                # The whole host pipeline collapses to an mmap row gather;
                # normalize happens on device (step.ingest_images).
                return self._pack.images[self._pack.rows[idx]]
            # uint8 rows / 255 reproduce decode_image's floats bit-for-bit
            # (the pack stores PIL's resize output pre-float-conversion), and
            # the in-place chain keeps the exact op order of normalize_image
            # (same bits) with one allocation instead of four — this IS the
            # packed path's hot loop, there's no decode to hide behind.
            out = self._pack.images[self._pack.rows[idx]].astype(np.float32)
            out /= 255.0
            out -= _MEAN
            out /= _STD
            return out
        if self.native_decode:
            from mpi_pytorch_tpu import native

            paths = [
                os.path.join(self.manifest.img_dir, self.manifest.filenames[i]) for i in idx
            ]
            # Items the C decoder refuses fall back per path; the fallback
            # rides the same retry/quarantine discipline as the PIL pool
            # (a quarantined item returns a zero image — its label is
            # masked by _masked_labels, so the content never trains).
            row_of = {}
            for k, p in enumerate(paths):
                row_of.setdefault(p, int(idx[k]))

            def robust_fallback(p):
                img = self._decode_with_retries(row_of[p])
                if img is None:
                    return np.zeros((*self.image_size, 3), np.float32)
                return img

            return native.decode_batch(
                paths,
                self.image_size,
                _MEAN,
                _STD,
                threads=self.num_workers,
                prescale_margin=self.decode_prescale,
                fallback=robust_fallback,
            )
        rows = list(pool.map(self._decode_with_retries, idx))
        bad = [k for k, r in enumerate(rows) if r is None]
        if bad:
            # Substitute quarantined rows with real decoded content (the
            # _cyclic_fill rationale: BN statistics span the whole batch,
            # so substitutes should be real pixels, not zeros) — zeros only
            # when the entire batch failed. Labels mask either way.
            good = [k for k, r in enumerate(rows) if r is not None]
            fill_dtype = np.uint8 if self.raw_uint8 else np.float32
            for n, k in enumerate(bad):
                rows[k] = (
                    rows[good[n % len(good)]]
                    if good
                    else np.zeros((*self.image_size, 3), fill_dtype)
                )
        return np.stack(rows)

    def wait_cache_complete(self) -> bool:
        """Join any in-flight cache-filling thread (the backfill keeps
        running after an early consumer close), then surface a decode error
        the closed consumer never saw. True when the cache is complete."""
        t = self._fill_thread
        if t is not None and t.is_alive():
            t.join()
        if self._cache_fill_error is not None:
            err, self._cache_fill_error = self._cache_fill_error, None
            raise err
        return self._cache_complete

    def adopt_cache(self, other: "DataLoader") -> bool:
        """Share ``other``'s completed host cache (by reference) when the two
        loaders walk the same data the same way — e.g. the validation loader
        adopting the train loader's cache under ``val_on_train`` semantics,
        instead of decoding a second full copy of the identical shard."""
        if (
            other._cache_images is not None
            and other._cache_complete
            and len(other.manifest) == len(self.manifest)
            and other.manifest.filenames == self.manifest.filenames
            and other.manifest.img_dir == self.manifest.img_dir
            and other.image_size == self.image_size
            and other.image_dtype == self.image_dtype
            and other.synthetic == self.synthetic
            and other.native_decode == self.native_decode
            and other.decode_prescale == self.decode_prescale
            and (other._pack.stem if other._pack else None)
            == (self._pack.stem if self._pack else None)
        ):
            self._cache_images = other._cache_images
            self._cache_complete = True
            # Rows the source loader quarantined while filling stay masked
            # here too — the cache holds their substitute pixels.
            self._quarantined |= other._quarantined
            return True
        return False

    def epoch(
        self, epoch: int = 0, start_batch: int = 0
    ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """Iterate one epoch of batches, prefetched in the background.

        ``start_batch`` fast-forwards past the first k batches WITHOUT
        decoding them: the ``(seed, epoch)`` visit order is deterministic,
        so the consumed prefix is just an offset into ``epoch_order`` — the
        exact-step mid-epoch resume dataflow (train/trainer.py). Applies
        identically to the streaming, RAM-cache, and packed-mmap paths
        (all three walk the same order)."""
        n = len(self.manifest)
        order = epoch_order(self.seed, epoch, n, self.shuffle)
        nb = len(self)
        self._cur_epoch = epoch
        start_batch = max(0, min(start_batch, nb))
        if nb - start_batch == 0:
            return iter(())

        if self.host_cache:
            # Serialize with an in-flight filling epoch: two producers over
            # the same cache arrays would double-decode the shard (and the
            # join is exactly the remaining decode work either way).
            self.wait_cache_complete()

        if self.host_cache and self._cache_complete:
            # Slicing RAM is not worth a producer thread; the (seed, epoch)
            # order is identical to the streaming walk, so trajectories match.
            cache = self._cache_images

            def cached_gen() -> Iterator[tuple[np.ndarray, np.ndarray]]:
                for b in range(start_batch, nb):
                    idx = order[b * self.batch_size : (b + 1) * self.batch_size]
                    yield cache[idx], self._masked_labels(idx)

            return cached_gen()

        # Cache-as-you-stream: the filling epoch IS a normal streaming epoch
        # (decode overlapped with the consumer via the producer thread), with
        # each decoded batch additionally scattered into the cache array and
        # marked in a filled mask. Whatever the epoch never visits — tail
        # rows under drop_remainder, whole batches when the consumer stops
        # early (multi-host globally-truncated step counts close the iterator
        # after n_steps) — is backfilled at the end, in the background if the
        # consumer is already gone, so the cache ALWAYS completes.
        fill_cache = self.host_cache
        if fill_cache and self._cache_images is None:
            self._cache_images = np.empty(
                (n, *self.image_size, 3), self.image_dtype
            )
            self._cache_filled = np.zeros(n, bool)

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def put_or_abandon(item) -> bool:
            # Bounded put that gives up once the consumer is gone — never
            # blocks forever on a full queue. Returns whether it enqueued.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.5)
                    return True
                except queue.Full:
                    continue
            return False

        def decode_one_batch(idx, pool):
            stacked = self._load_batch(idx, pool)
            if stacked.dtype != self.image_dtype:
                stacked = stacked.astype(self.image_dtype)
            if fill_cache:
                self._cache_images[idx] = stacked
                self._cache_filled[idx] = True
            return stacked

        def producer() -> None:
            error = None
            try:
                with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
                    for b in range(start_batch, nb):
                        if stop.is_set():
                            break  # consumer gone; still backfill the cache below
                        idx = order[b * self.batch_size : (b + 1) * self.batch_size]
                        stacked = decode_one_batch(idx, pool)
                        # Labels AFTER decode: a row quarantined by this
                        # very batch must already be masked.
                        put_or_abandon((stacked, self._masked_labels(idx)))
                    if fill_cache and not self._cache_complete:
                        # Backfill whatever this epoch didn't decode. With a
                        # live consumer this is at most the drop_remainder
                        # tail (sub-batch, done before the sentinel); after an
                        # early close it runs in the background — the stopped
                        # consumer isn't waiting on the queue.
                        missing = np.nonzero(~self._cache_filled)[0]
                        for s in range(0, len(missing), self.batch_size):
                            decode_one_batch(missing[s : s + self.batch_size], pool)
                        self._cache_complete = True
            except BaseException as e:  # surface decode errors to the consumer
                error = e
            finally:
                # None sentinel, or the exception to re-raise. If the
                # consumer is already gone (early close), park the error for
                # wait_cache_complete() so a backfill failure is never silent.
                if not put_or_abandon(error) and error is not None:
                    self._cache_fill_error = error

        t = threading.Thread(target=producer, daemon=True)
        if fill_cache:
            self._fill_thread = t
        t.start()

        def gen() -> Iterator[tuple[np.ndarray, np.ndarray]]:
            try:
                while True:
                    item = q.get()
                    if item is None:
                        break
                    if isinstance(item, BaseException):
                        raise item
                    yield item
            finally:
                stop.set()

        return gen()
