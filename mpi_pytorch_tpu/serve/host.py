"""One serving host as a PROCESS: ``python -m mpi_pytorch_tpu.serve.host``.

The remote half of the fleet transport (ISSUE 12 / ROADMAP item 2): one
``InferenceServer`` stood up behind an extended ``ObsHTTPServer``, so the
fleet router can drive it over the wire exactly like it drives a
``LocalHost`` in-process. PR 9's fleet fixed the routing topology but
not the blast radius — every "host" shared one process; this entrypoint
is what makes "kill a host" mean killing a process.

Wire protocol (all bodies bounded, all reads timed — ``serve/http.py``):

- ``POST /submit`` — one request image as ``.npy`` bytes (the
  self-describing numpy wire format: shape + dtype + raw pixels). Replies
  ``202 {"req_id": N}``; the id keys the result long-poll. Admission
  backpressure surfaces as **HTTP 429** with a ``retry_after_ms`` JSON
  body (and a ``Retry-After`` header) mapped from the server's typed
  ``QueueFullError`` — the hint crosses the wire intact. A closing server
  replies 503; a request-fault (bad shape, undecodable payload) replies
  400 and is NEVER retried by a sane client — it would fail anywhere.
- ``GET /result/<req_id>?timeout_s=S`` — long-poll for the prediction:
  200 with ``.npy`` top-k bytes when done, **408** when still pending
  after the slice (re-poll), 404 for an unknown id (a RESTARTED process
  does not know its predecessor's ids — the client classifies that as a
  host failure and the router re-dispatches). Delivery is idempotent: a
  delivered result stays fetchable until the reaper expires it, so a
  response lost on the wire costs a re-poll, not the answer.
- ``POST /control`` — ``{"op": "set_max_wait_ms"|"set_active_buckets"|
  "set_precision"|"shutdown", ...}``: the retune/lifecycle surface the
  fleet controller and supervisor drive (each op maps 1:1 onto the
  ``HostHandle`` method of the same name; invalid retunes are the same
  typed 400 the in-process call would raise).
- ``GET /statsz`` / ``/metricsz`` / ``/metrics`` / ``/healthz`` — the
  probe surface (``/healthz`` carries the static host facts: queue
  capacity, compiled buckets, precisions, pid — plus ``time``, the
  collector's clock-probe read). ``/metricsz`` snapshots carry a
  monotonic ``seq`` + process ``start_ts`` so a scraper can tell a
  counter reset (restart) from a negative delta (ISSUE 13).
- ``GET /tracez?since=N`` — the bounded span-export ring: finished
  host-side spans (queue/preprocess/device per traced request), exported
  incrementally by cursor to the fleet collector. A ``Traceparent``
  header on ``POST /submit`` / ``GET /result`` threads the front door's
  trace id through this host's spans (W3C-style; ``obs/context.py``).

Readiness: after warmup the process atomically writes ``--serve-port-file``
(JSON ``{"port", "pid", "host_index"}``) and prints a ``SERVE_HOST_READY``
line — the supervisor's spawn handshake. SIGTERM/SIGINT drain gracefully:
the batcher flushes queued requests, waiting long-polls deliver, then the
HTTP listener closes. Warm-start recipe: point ``--compilation-cache-dir``
at a shared directory and a (re)started host's warmup compiles become
cache hits — the startup cost of failover/scale-up is placement + warmup
execution, not XLA compilation (``compiles_after_warmup`` stays 0 either
way; the cache is what makes the WALL CLOCK of "spawn a host" cheap).
"""

from __future__ import annotations

import io
import itertools
import json
import math
import os
import signal
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError

import numpy as np

from mpi_pytorch_tpu.serve.batcher import (
    QueueFullError,
    ServeError,
    ServerClosedError,
)
from mpi_pytorch_tpu.serve.http import ObsHTTPServer


def _npy_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr, allow_pickle=False)
    return buf.getvalue()


def load_npy_bytes(body: bytes) -> np.ndarray:
    """The wire decode (shared with the client side): strict, no pickle."""
    return np.load(io.BytesIO(body), allow_pickle=False)


class _NullRegistry:
    """Registry stand-in for duck-typed servers without one (tests)."""

    def prometheus_text(self) -> str:
        return ""

    def snapshot(self) -> dict:
        return {"counters": {}, "gauges": {}, "histograms": {}}


class ServingHost:
    """HTTP front over one (duck-typed) ``InferenceServer``.

    Owns the wire surface only: request ids, the result table with its
    idempotent-delivery reaper, and the typed-error → status mapping.
    The server underneath is anything with ``submit(image) -> Future``
    (plus the stats/retune surface when mounted on the real thing) —
    which is what lets the transport tests drive the full wire path
    without a jax backend behind it.
    """

    def __init__(
        self,
        server,
        *,
        port: int = 0,
        read_timeout_s: float = 10.0,
        max_body_bytes: int = 64 << 20,
        poll_slice_s: float = 10.0,
        result_ttl_s: float = 60.0,
        result_hard_ttl_s: float = 600.0,
        wire: bool = False,
        wire_port: int = 0,
        logger=None,
    ):
        from mpi_pytorch_tpu.utils.logging import run_logger

        self.server = server
        self._logger = logger or run_logger()
        self._poll_slice_s = float(poll_slice_s)
        self._result_ttl_s = float(result_ttl_s)
        self._result_hard_ttl_s = float(result_hard_ttl_s)
        # req_id -> [future, t_created, t_delivered|None]; delivered
        # results stay until the reaper expires them (idempotent /result).
        self._results: dict[int, list] = {}
        self._results_lock = threading.Lock()
        self._ids = itertools.count()
        self._closed = False
        self.closed_event = threading.Event()
        # Framed data plane (ISSUE 16): a WireListener mounted NEXT TO the
        # HTTP surface — submit/result move to persistent binary-framed
        # connections, while probes/control/facts stay on HTTP (cold
        # paths; one wire protocol per temperature). The port rides
        # /healthz (and the readiness file) as ``wire_port``.
        self.wire = None
        if wire:
            from mpi_pytorch_tpu.serve.wire import WireListener

            host_index = getattr(server, "host_index", None)
            self.wire = WireListener(
                self._wire_submit,
                host_index=-1 if host_index is None else host_index,
                port=wire_port,
                logger=self._logger,
            )
        healthz_fn = getattr(server, "_healthz", None)
        if self.wire is not None and healthz_fn is not None:
            base_healthz, wire_listener = healthz_fn, self.wire

            def healthz_fn():
                return dict(base_healthz(), wire_port=wire_listener.port)

        registry = getattr(server, "_registry", None) or _NullRegistry()
        metricsz = getattr(server, "registry_snapshot", None)
        self.http = ObsHTTPServer(
            registry,
            healthz=healthz_fn,
            port=port,
            metricsz=metricsz,
            get_routes={"/result/": self._handle_result,
                        "/statsz": self._handle_statsz,
                        "/tracez": self._handle_tracez},
            post_routes={"/submit": self._handle_submit,
                         "/control": self._handle_control},
            read_timeout_s=read_timeout_s,
            max_body_bytes=max_body_bytes,
        )
        self.port = self.http.port
        self._reaper_stop = threading.Event()
        self._reaper = threading.Thread(
            target=self._reap_loop, name="serve-host-reaper", daemon=True
        )
        self._reaper.start()

    @property
    def wire_port(self) -> int | None:
        """The framed listener's port (None on an HTTP-only host)."""
        return self.wire.port if self.wire is not None else None

    def _wire_submit(self, image, model, traceparent):
        """The WireListener's coupling into the request path: same typed
        semantics as POST /submit, minus the HTTP wrapping — typed
        ServeErrors propagate (the listener maps them to ERROR frames
        with the taxonomy intact)."""
        from mpi_pytorch_tpu.obs.context import parse_traceparent

        kwargs = {}
        ctx = parse_traceparent(traceparent)
        if ctx is not None:
            kwargs["trace"] = ctx
        if model is not None:
            kwargs["model"] = model
        try:
            return self.server.submit(image, **kwargs)
        except TypeError:
            if model is None:
                raise
            raise ServeError(
                f"host is not multi-tenant (model={model!r})"
            ) from None

    # ------------------------------------------------------------- routes

    @staticmethod
    def _json(status: int, payload: dict, headers=None):
        return (status, "application/json",
                json.dumps(payload).encode(), headers or {})

    def _handle_submit(self, path, query, body):
        try:
            image = load_npy_bytes(body)
        except Exception as e:  # noqa: BLE001 — malformed wire payload
            return self._json(400, {
                "error": "bad_request", "taxonomy": "request",
                "detail": f"request body is not .npy bytes ({e})",
            })
        # Tenant routing over the wire (ISSUE 14): POST /submit?model=m
        # names the tenant on a multi-model (zoo) host. Naming one on an
        # untenanted host is a request fault (400), never host-shaped.
        model = None
        for part in query.split("&"):
            if part.startswith("model="):
                import urllib.parse

                model = urllib.parse.unquote(part[6:])
        # The trace thread crossing the wire (ISSUE 13): a traceparent
        # header minted at the fleet front door parents this host's
        # queue/preprocess/device spans; a malformed or absent header is
        # an untraced request, never an error.
        from mpi_pytorch_tpu.obs.context import parse_traceparent

        ctx = parse_traceparent(self.http.request_headers().get("Traceparent"))
        try:
            kwargs = {}
            if ctx is not None:
                kwargs["trace"] = ctx
            if model is not None:
                kwargs["model"] = model
            try:
                fut = self.server.submit(image, **kwargs)
            except TypeError:
                if model is None:
                    raise
                return self._json(400, {
                    "error": "serve_error", "taxonomy": "request",
                    "detail": f"host is not multi-tenant (model={model!r})",
                })
        except QueueFullError as e:
            hint = e.retry_after_ms
            headers = {}
            if hint is not None:
                headers["Retry-After"] = max(1, math.ceil(hint / 1e3))
            return self._json(429, {
                "error": "queue_full", "detail": str(e),
                "retry_after_ms": hint,
                # ISSUE 14: the rejection names its tenant so a client
                # (or the router) backs off the right budget.
                "model": getattr(e, "model", None),
            }, headers)
        except ServerClosedError as e:
            return self._json(503, {"error": "closed", "detail": str(e)})
        except ServeError as e:
            return self._json(400, {
                "error": "serve_error", "taxonomy": "request",
                "detail": str(e),
            })
        rid = next(self._ids)
        with self._results_lock:
            self._results[rid] = [fut, time.monotonic(), None]
        return self._json(202, {"req_id": rid})

    def _handle_result(self, path, query, body):
        try:
            rid = int(path.rsplit("/", 1)[1])
        except ValueError:
            return self._json(400, {"error": "bad_request",
                                    "taxonomy": "request",
                                    "detail": "non-integer req_id"})
        timeout = self._poll_slice_s
        for part in query.split("&"):
            if part.startswith("timeout_s="):
                try:
                    timeout = min(max(float(part[10:]), 0.0), 30.0)
                except ValueError:
                    pass
        with self._results_lock:
            entry = self._results.get(rid)
        if entry is None:
            return self._json(404, {"error": "unknown_req_id"})
        fut = entry[0]
        try:
            preds = fut.result(timeout=timeout)
        except FutureTimeoutError:
            return self._json(408, {"error": "pending"})
        except QueueFullError as e:
            # Cannot happen post-admission today; mapped for completeness.
            return self._json(429, {"error": "queue_full", "detail": str(e),
                                    "retry_after_ms": e.retry_after_ms})
        except ServerClosedError as e:
            return self._json(503, {"error": "closed", "detail": str(e)})
        except ServeError as e:
            # The REQUEST's own fault (preprocess crash on its payload,
            # bad shape): the client must propagate, never re-dispatch.
            return self._json(400, {"error": "serve_error",
                                    "taxonomy": "request",
                                    "detail": str(e)})
        except Exception as e:  # noqa: BLE001 — host-shaped failure
            return self._json(500, {"error": "internal", "taxonomy": "host",
                                    "detail": f"{type(e).__name__}: {e}"})
        with self._results_lock:
            if rid in self._results:
                self._results[rid][2] = time.monotonic()  # delivered
        return (200, "application/octet-stream",
                _npy_bytes(np.asarray(preds)), {})

    def _handle_tracez(self, path, query, body):
        """The bounded span-export ring (ISSUE 13): incremental by
        ``?since=<cursor>``; the payload's ``start_ts`` is the recorder
        generation, so a collector whose cursor outlived this process's
        predecessor knows to rewind."""
        since = 0
        for part in query.split("&"):
            if part.startswith("since="):
                try:
                    since = int(part[6:])
                except ValueError:
                    pass
        traces_fn = getattr(self.server, "traces", None)
        if traces_fn is None:
            return self._json(200, {"spans": [], "next_seq": 0,
                                    "dropped": 0, "start_ts": None})
        return self._json(200, traces_fn(since))

    def _handle_statsz(self, path, query, body):
        stats_fn = getattr(self.server, "stats", None)
        stats = stats_fn() if stats_fn else {}
        # by_bucket keys are ints — JSON objects stringify them; the
        # remote consumers read the flat counters, so stringified is fine.
        if "by_bucket" in stats:
            stats = dict(stats, by_bucket={
                str(k): v for k, v in stats["by_bucket"].items()
            })
        return self._json(200, stats)

    def _handle_control(self, path, query, body):
        try:
            req = json.loads(body.decode())
            op = req["op"]
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            return self._json(400, {"error": "bad_request",
                                    "taxonomy": "request",
                                    "detail": f"malformed control body ({e})"})
        try:
            if op == "set_max_wait_ms":
                self.server.set_max_wait_ms(float(req["value"]))
            elif op == "set_active_buckets":
                self.server.set_active_buckets(
                    tuple(int(b) for b in req["value"])
                )
            elif op == "set_precision":
                self.server.set_precision(str(req["value"]))
            elif op in ("ensure_model", "evict_model"):
                # The zoo residency surface (ISSUE 14): the router's
                # cold-load spill and the operator's evict, over the wire.
                fn = getattr(self.server, op, None)
                if fn is None:
                    return self._json(400, {
                        "error": "serve_error", "taxonomy": "request",
                        "detail": f"host is not multi-tenant ({op})",
                    })
                fn(str(req["value"]))
            elif op == "shutdown":
                self.shutdown_async(drain=bool(req.get("drain", True)))
            else:
                return self._json(400, {"error": "unknown_op", "op": op})
        except ServeError as e:
            return self._json(400, {"error": "serve_error",
                                    "taxonomy": "request",
                                    "detail": str(e)})
        except (KeyError, TypeError, ValueError) as e:
            return self._json(400, {"error": "bad_request",
                                    "detail": f"{type(e).__name__}: {e}"})
        return self._json(200, {"ok": True, "op": op})

    # ---------------------------------------------------------- lifecycle

    def _reap_loop(self) -> None:
        while not self._reaper_stop.wait(5.0):
            now = time.monotonic()
            with self._results_lock:
                stale = [
                    rid for rid, (fut, t0, t_done) in self._results.items()
                    if (t_done is not None
                        and now - t_done > self._result_ttl_s)
                    or now - t0 > self._result_hard_ttl_s
                ]
                for rid in stale:
                    del self._results[rid]

    def shutdown_async(self, drain: bool = True) -> None:
        """The /control shutdown: run the (slow, thread-joining) close off
        the handler thread so the control reply goes out first."""
        threading.Thread(
            target=self.close, kwargs={"drain": drain},
            name="serve-host-shutdown", daemon=True,
        ).start()

    def close(self, drain: bool = True) -> None:
        with self._results_lock:
            if self._closed:
                return
            self._closed = True
        # Server first: a graceful drain resolves the outstanding futures
        # WHILE the HTTP surface is still up, so waiting long-polls
        # deliver their results instead of dying with the listener.
        try:
            self.server.close(drain=drain)
        except TypeError:  # duck-typed servers without the drain kwarg
            self.server.close()
        self._reaper_stop.set()
        self.http.close()
        # Wire listener LAST: a graceful drain resolves in-flight futures
        # above, and their done-callbacks must still find live
        # connections to write RESULT frames into.
        if self.wire is not None:
            self.wire.close()
        self.closed_event.set()


def main(argv=None) -> int:
    """Entrypoint: stand up one serving-host process and serve until a
    signal (or a /control shutdown) takes it down."""
    from mpi_pytorch_tpu.config import parse_config
    from mpi_pytorch_tpu.serve.server import InferenceServer
    from mpi_pytorch_tpu.utils.logging import run_logger

    cfg = parse_config(argv)
    logger = run_logger()
    host_index = cfg.serve_host_index if cfg.serve_host_index >= 0 else None
    if cfg.serve_models:
        # Multi-model tenancy (ISSUE 14): this process serves the whole
        # zoo spec — per-tenant pipelines behind the same wire surface
        # (requests carry ?model=, /healthz advertises the resident set).
        from mpi_pytorch_tpu.serve.zoo import ZooServer

        server = ZooServer(cfg, host_index=host_index)
    else:
        server = InferenceServer(cfg, host_index=host_index)
    host = ServingHost(
        server,
        port=max(0, cfg.serve_port),
        read_timeout_s=cfg.serve_read_timeout_s,
        wire=cfg.serve_transport == "framed",
        logger=logger,
    )
    payload = {
        "port": host.port, "pid": os.getpid(),
        "host_index": -1 if host_index is None else host_index,
    }
    if host.wire_port is not None:
        # ISSUE 16: the framed data-plane port, for WireHost's dial
        # (absent on http-transport hosts — old readers are unaffected).
        payload["wire_port"] = host.wire_port
    if cfg.serve_port_file:
        # Atomic: the supervisor polls for this file, and a torn read of
        # a half-written JSON must be impossible, not just unlikely.
        tmp = f"{cfg.serve_port_file}.tmp.{os.getpid()}"
        os.makedirs(os.path.dirname(cfg.serve_port_file) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, cfg.serve_port_file)
    print(
        f"SERVE_HOST_READY host=127.0.0.1 port={host.port} "
        f"pid={os.getpid()} index={payload['host_index']}",
        flush=True,
    )
    logger.info(
        "serve host %s: listening on 127.0.0.1:%d (pid %d)",
        server.name, host.port, os.getpid(),
    )

    def _graceful(signum, frame):
        logger.info("serve host: signal %d — draining", signum)
        host.shutdown_async(drain=True)

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    host.closed_event.wait()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
