"""ViT family: registry integration, SP-strategy numerics (full ≡ ring ≡
Ulysses inside the model), remat agreement, the train step end-to-end, and
the sp_strategy guard for sequence-free architectures.

The load-bearing property: a ViT built with ``sp_strategy='ring'`` or
``'ulysses'`` computes the SAME function as the plain model — sequence
parallelism is an execution layout, not a different network.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from mpi_pytorch_tpu.models import create_model_bundle, initialize_model
from mpi_pytorch_tpu.models.vit import VisionTransformer

# Tiny config: 32px / patch 4 → 64 tokens (divisible by 8 shards); 8 heads
# (divisible by 8 for Ulysses).
TINY = dict(
    num_classes=10, patch_size=4, hidden=64, depth=2, num_heads=8, mlp_dim=128
)


@pytest.fixture(scope="module")
def sp_mesh():
    dev = np.asarray(jax.devices()[:8]).reshape(8, 1)
    return Mesh(dev, ("seq", "unused"))


@pytest.fixture(scope="module")
def tiny_vit():
    model = VisionTransformer(**TINY)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((4, 32, 32, 3)), jnp.float32
    )
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    return model, variables, x


def test_vit_forward_shape_and_params(tiny_vit):
    model, variables, x = tiny_vit
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (4, 10)
    # Exact param count: patch embed + pos + 2 blocks + final LN + head.
    h, mlp, heads, p = TINY["hidden"], TINY["mlp_dim"], TINY["num_heads"], TINY["patch_size"]
    tokens = (32 // p) ** 2
    patch = 3 * p * p * h + h
    pos = tokens * h
    per_block = (
        4 * (h * h + h)          # q, k, v, out projections
        + (h * mlp + mlp) + (mlp * h + h)  # MLP
        + 2 * 2 * h              # two LayerNorms
    )
    total = patch + pos + TINY["depth"] * per_block + 2 * h + (h * 10 + 10)
    got = sum(x.size for x in jax.tree_util.tree_leaves(variables["params"]))
    assert got == total


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_vit_sp_matches_plain(tiny_vit, sp_mesh, strategy):
    model, variables, x = tiny_vit
    sp_model = VisionTransformer(**TINY, sp_strategy=strategy, sp_mesh=sp_mesh)
    got = sp_model.apply(variables, x, train=False)
    want = model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_vit_sp_grads_match_plain(tiny_vit, sp_mesh, strategy):
    model, variables, x = tiny_vit
    sp_model = VisionTransformer(**TINY, sp_strategy=strategy, sp_mesh=sp_mesh)

    def loss(m, params):
        out = m.apply({"params": params}, x, train=False)
        return jnp.sum(out * out)

    g_sp = jax.grad(lambda p: loss(sp_model, p))(variables["params"])
    g_pl = jax.grad(lambda p: loss(model, p))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g_sp), jax.tree_util.tree_leaves(g_pl)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_vit_remat_blocks_matches_plain(tiny_vit):
    model, variables, x = tiny_vit
    remat_model = VisionTransformer(**TINY, remat_blocks=True)

    def loss(m, params):
        return jnp.sum(m.apply({"params": params}, x, train=False) ** 2)

    np.testing.assert_allclose(
        float(loss(remat_model, variables["params"])),
        float(loss(model, variables["params"])),
        rtol=1e-6,
    )
    g_r = jax.grad(lambda p: loss(remat_model, p))(variables["params"])
    g_p = jax.grad(lambda p: loss(model, p))(variables["params"])
    for a, b in zip(jax.tree_util.tree_leaves(g_r), jax.tree_util.tree_leaves(g_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


@pytest.mark.slow
def test_vit_trains_through_standard_step():
    """The family plugs into the same train step as the CNN zoo."""
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step

    bundle, variables = create_model_bundle(
        "vit_s16", 10, rng=jax.random.PRNGKey(0), image_size=32
    )
    assert bundle.has_aux_logits is False
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
    )
    rng = np.random.default_rng(2)
    images = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    step = make_train_step(jnp.float32)
    losses = []
    for _ in range(3):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_trainer_config_wires_sp_and_ep(tmp_path):
    """--sp-strategy / --expert-parallel reach the model through
    build_training: the bundle's model carries the strategy and a
    seq/expert mesh over the training mesh's devices (numerics of those
    paths are covered by the module-level SP/EP equality tests)."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.train.trainer import build_training

    cfg = Config(
        model_name="vit_s16", num_classes=1000, batch_size=8,
        width=64, height=64, synthetic_data=True, sp_strategy="ring",
        checkpoint_dir=str(tmp_path), validate=False,
    )
    _, bundle, _, _ = build_training(cfg)
    assert bundle.model.sp_strategy == "ring"
    assert bundle.model.sp_mesh.axis_names[0] == "seq"

    cfg2 = Config(
        model_name="vit_moe_s16", num_classes=1000, batch_size=8,
        width=64, height=64, synthetic_data=True, expert_parallel=True,
        checkpoint_dir=str(tmp_path), validate=False,
    )
    _, bundle2, _, _ = build_training(cfg2)
    assert bundle2.model.ep_mesh.axis_names[0] == "expert"
    assert bundle2.model.moe_every == 2


@pytest.mark.slow
def test_inference_config_wires_sp_and_ep(tmp_path):
    """The eval driver mirrors the trainer's SP/EP model wiring."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.evaluate import build_inference

    cfg = Config(
        model_name="vit_moe_s16", num_classes=1000, batch_size=8,
        width=64, height=64, synthetic_data=True, expert_parallel=True,
        checkpoint_dir=str(tmp_path), validate=False,
    )
    _, bundle, _, _ = build_inference(cfg)
    assert bundle.model.ep_mesh.axis_names[0] == "expert"


def test_config_rejects_bad_sp_strategy():
    from mpi_pytorch_tpu.config import Config

    with pytest.raises(ValueError, match="sp_strategy"):
        Config(sp_strategy="rings").validate_config()


def test_registry_rejects_sp_on_cnn():
    with pytest.raises(ValueError, match="vit"):
        initialize_model("resnet18", 10, sp_strategy="ring")


def test_vit_rejects_bad_patch_grid():
    model = VisionTransformer(**TINY)
    with pytest.raises(ValueError, match="divisible"):
        model.init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, 30, 30, 3)), train=False,
        )


def test_qkv_fused_parity():
    """--qkv-fused: identical param tree, bit-identical INIT values (the
    _ProjParams kernel init replicates DenseGeneral's flatten-then-reshape
    fan-in), equal forward and gradients — the checkpoint-interchange
    claim, pinned (it depends on flax DenseGeneral internals)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from mpi_pytorch_tpu.models.vit import VisionTransformer

    kw = dict(num_classes=10, patch_size=8, hidden=32, depth=2,
              num_heads=4, mlp_dim=64)
    vu = VisionTransformer(**kw)
    vf = VisionTransformer(**kw, qkv_fused=True)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 16, 16, 3)), jnp.float32
    )
    p1 = vu.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    p2 = vf.init({"params": jax.random.PRNGKey(0)}, x, train=False)
    assert jax.tree.structure(p1) == jax.tree.structure(p2)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    o1 = vu.apply(p1, x, train=False)
    o2 = vf.apply(p1, x, train=False)  # SAME params through both layouts
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=2e-5, atol=2e-5)
    g1 = jax.grad(lambda p: jnp.sum(vu.apply(p, x, train=False) ** 2))(p1)
    g2 = jax.grad(lambda p: jnp.sum(vf.apply(p, x, train=False) ** 2))(p1)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
