"""Train state: params + BN running stats + optimizer state + step + rng.

The reference's analogue is the (model, optimizer) pair of torch objects
(``main.py:121-125``) whose state lives implicitly in mutable modules. Here
it is one immutable pytree, which is what makes the whole step jittable and
shardable.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any  # None for BN-free models (alexnet, squeezenet)
    opt_state: Any
    rng: jax.Array
    # static (non-pytree) fields:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, variables: dict, tx, rng: jax.Array) -> "TrainState":
        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats"),
            opt_state=tx.init(params),
            rng=rng,
            apply_fn=apply_fn,
            tx=tx,
        )

    @property
    def variables(self) -> dict:
        v = {"params": self.params}
        if self.batch_stats is not None:
            v["batch_stats"] = self.batch_stats
        return v


def make_optimizer(
    learning_rate: float, trainable_mask: Any | None = None
) -> optax.GradientTransformation:
    """Adam(lr) (≙ ``main.py:125``). With ``feature_extract``, non-head params
    get zero updates — the optax expression of ``requires_grad=False``
    (reference ``models.py:5-13``)."""
    tx = optax.adam(learning_rate)
    if trainable_mask is None:
        return tx
    labels = jax.tree_util.tree_map(lambda t: "train" if t else "freeze", trainable_mask)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )
