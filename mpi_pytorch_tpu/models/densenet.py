"""DenseNet-121 in Flax (NHWC). Parity with the reference's torchvision
densenet121 factory (``models.py:74-81``): growth 32, block config
(6, 12, 24, 16), BN-ReLU-Conv bottleneck layers with dense concatenation.

TPU note: the dense-block concatenations are the HBM-bandwidth-heavy part of
this zoo (BASELINE.json calls densenet 'concat-heavy'); keeping NHWC means
every concat is on the minor-most lane axis, which XLA fuses into the
consuming conv without a relayout.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from mpi_pytorch_tpu.models.common import (
    FusedStemBNReluPool,
    batch_norm,
    global_avg_pool,
    max_pool,
)


class DenseLayer(nn.Module):
    growth_rate: int
    bn_size: int = 4
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        y = batch_norm("norm1", dtype=self.dtype, axis_name=self.bn_axis_name)(
            x, use_running_average=not train
        )
        y = nn.relu(y)
        y = nn.Conv(
            self.bn_size * self.growth_rate, (1, 1), use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="conv1",
        )(y)
        y = batch_norm("norm2", dtype=self.dtype, axis_name=self.bn_axis_name)(
            y, use_running_average=not train
        )
        y = nn.relu(y)
        y = nn.Conv(
            self.growth_rate, (3, 3), padding=1, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="conv2",
        )(y)
        return jnp.concatenate([x, y], axis=-1)


class Transition(nn.Module):
    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool) -> jnp.ndarray:
        x = batch_norm("norm", dtype=self.dtype, axis_name=self.bn_axis_name)(
            x, use_running_average=not train
        )
        x = nn.relu(x)
        x = nn.Conv(
            self.features, (1, 1), use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="conv",
        )(x)
        return nn.avg_pool(x, (2, 2), strides=(2, 2))


class DenseNet(nn.Module):
    block_config: Sequence[int]
    num_classes: int
    growth_rate: int = 32
    num_init_features: int = 64
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    bn_axis_name: str | None = None
    # Checkpoint each DenseLayer (nn.remat): densenet is the most
    # activation-heavy zoo member (every layer's concat input stays live for
    # backward); per-layer recompute caps that at one layer's activations.
    # Param tree paths are unchanged (lifted transforms preserve scopes).
    remat_blocks: bool = False
    # Fuse norm0+relu+maxpool(3,2,1) into the ops/fused_stem.py Pallas
    # kernel pair — densenet's torchvision stem (features.conv0..pool0) is
    # geometrically IDENTICAL to the resnet stem the kernel was built for
    # (7×7/s2/p3 conv, C=64, BN, relu, 3×3/s2/p1 pool). FusedStemBNReluPool
    # mirrors flax BatchNorm's variable tree, so checkpoints interchange
    # with the unfused stem. Ships flag-gated pending the chip A/B: the
    # stem tail is only ≈3% of densenet's roofline bound (docs/RESULTS.md
    # §4 — vs ≈17% for resnet18), so unlike the resnet family it is NOT
    # the zoo-bench default.
    fused_stem: bool = False
    # Multi-chip fused stem: the mesh whose leading (data) axis the Mosaic
    # call is shard_map-partitioned over (ops/fused_stem.py, Multi-chip).
    dp_mesh: Any = None

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = False) -> jnp.ndarray:
        x = nn.Conv(
            self.num_init_features, (7, 7), strides=(2, 2), padding=3, use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype, name="conv0",
        )(x)
        if self.fused_stem:
            if self.bn_axis_name is not None:
                raise ValueError("fused_stem does not support sync-BN (bn_axis_name)")
            x = FusedStemBNReluPool(
                dtype=self.dtype, param_dtype=self.param_dtype,
                dp_mesh=self.dp_mesh, name="norm0",
            )(x, use_running_average=not train)
        else:
            x = batch_norm("norm0", dtype=self.dtype, axis_name=self.bn_axis_name)(
                x, use_running_average=not train
            )
            x = nn.relu(x)
            x = max_pool(x, 3, 2, padding=1)

        layer_cls = (
            nn.remat(DenseLayer, static_argnums=(2,))  # (self, x, train)
            if self.remat_blocks
            else DenseLayer
        )
        features = self.num_init_features
        for i, n_layers in enumerate(self.block_config):
            for j in range(n_layers):
                x = layer_cls(
                    growth_rate=self.growth_rate, dtype=self.dtype,
                    param_dtype=self.param_dtype, bn_axis_name=self.bn_axis_name,
                    name=f"denseblock{i + 1}_layer{j + 1}",
                )(x, train)
            features += n_layers * self.growth_rate
            if i != len(self.block_config) - 1:
                features //= 2
                x = Transition(
                    features=features, dtype=self.dtype, param_dtype=self.param_dtype,
                    bn_axis_name=self.bn_axis_name, name=f"transition{i + 1}",
                )(x, train)

        x = batch_norm("norm5", dtype=self.dtype, axis_name=self.bn_axis_name)(
            x, use_running_average=not train
        )
        x = nn.relu(x)
        x = global_avg_pool(x)
        # Head matmul in compute dtype; the loss computes softmax in float32.
        return nn.Dense(
            self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype, name="head"
        )(x)


def densenet121(num_classes: int, **kw: Any) -> DenseNet:
    return DenseNet(block_config=(6, 12, 24, 16), num_classes=num_classes, **kw)
