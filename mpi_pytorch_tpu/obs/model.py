"""Fitted per-phase latency model (ISSUE 18): learn device time and
queueing delay per (model, bucket, precision, residency) from the fleet's
own observability stream, then predict per-phase p99s for configs that
were never run.

Two fit sources, both already produced by the ISSUE 13 collector:

- ``fit_trace(path)`` — raw per-span durations from a fleet-trace JSONL,
  keyed by the v14 ``serve/request`` root attrs (model/bucket/precision).
- ``fit_phase_stats(stats, ...)`` — the aggregate
  ``FleetCollector.drain_phase_stats()`` dict for one known key, when raw
  spans are unavailable (e.g. a committed ``per_phase`` bench row).

Prediction is deliberately a *first-cut analytic* model, not a black
box — every number in ``predict()`` is reproducible from the explain
lines:

- ``serve/device``: fitted percentile for the chosen bucket; an unseen
  bucket borrows the nearest fitted bucket scaled linearly in rows (the
  explain line says so).
- ``serve/preprocess``: fitted percentile (config-independent host work).
- ``serve/queue``: ``max_wait_ms`` (the batching window the candidate
  config *chooses* to spend) plus a congestion term: an M/M/1-flavor
  ``device_p50 * rho / (1 - rho)`` below saturation, or — because a
  recorded workload is a finite burst — the end-of-burst backlog drain
  ``duration * (rho - 1)`` at/over saturation (``rho`` is offered
  requests/s over fleet service capacity).  Saturated candidates are
  flagged, and the drain term keeps them comparable (more hosts drain a
  smaller backlog) instead of collapsing onto one sentinel.

Calibration is stamped, not assumed: ``calibrate()`` records the max
relative per-phase error between a prediction and a replayed measurement
(ISSUE 18 acceptance checks the winner against exactly this number).
Like the rest of ``obs`` this module never imports jax.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .replay import Workload, _parse_span, _percentile

PHASES = ("serve/queue", "serve/preprocess", "serve/device")

#: Cap on any predicted congestion term — keeps arithmetic and JSON
#: well-defined for pathologically over-saturated candidates.
SATURATED_MS = 60_000.0


class ModelError(ValueError):
    """Typed refusal: the model cannot answer (nothing fitted for any
    compatible key, or a malformed candidate config)."""


@dataclass(frozen=True)
class FitKey:
    model: str | None
    bucket: int
    precision: str | None
    residency: str = "replicated"


@dataclass
class _KeyFit:
    samples: dict = field(default_factory=dict)   # phase -> [dur_ms]
    aggregates: dict = field(default_factory=dict)  # phase -> {count,p50,p99}


class PhaseLatencyModel:
    """Per-(model, bucket, precision, residency) device-time +
    queueing-delay model with stamped calibration."""

    def __init__(self):
        self._fits: dict = {}  # FitKey -> _KeyFit
        self.calibration_error_pct: float | None = None
        self.calibration_window: str | None = None

    # ------------------------------------------------------------- fitting

    def fit_trace(self, path: str, *,
                  default_residency: str = "replicated") -> int:
        """Fit from a fleet-trace JSONL.  Spans are grouped per trace; the
        ``serve/request`` root's v14 attrs key its ``serve/*`` children.
        Returns the number of requests fitted."""
        by_trace: dict = {}
        with open(path) as fh:
            for lineno, line in enumerate(fh, start=1):
                if not line.strip():
                    continue
                span = _parse_span(line, lineno)
                if span.get("trace"):
                    by_trace.setdefault(span["trace"], []).append(span)
        fitted = 0
        for spans in by_trace.values():
            serve_root = next(
                (s for s in spans
                 if s["name"] == "serve/request"
                 and (s.get("attrs") or {}).get("status") == "ok"),
                None)
            if serve_root is None:
                continue
            attrs = serve_root.get("attrs") or {}
            bucket = attrs.get("bucket")
            if not isinstance(bucket, int):
                continue  # pre-v14 recording: nothing to key on
            key = FitKey(model=attrs.get("model"), bucket=bucket,
                         precision=attrs.get("precision"),
                         residency=attrs.get("residency",
                                             default_residency))
            fit = self._fits.setdefault(key, _KeyFit())
            root_id = serve_root.get("span")
            device_id = next(
                (s.get("span") for s in spans
                 if s["name"] == "serve/device"
                 and s.get("parent") == root_id), None)
            for s in spans:
                if s["name"] in PHASES and s.get("parent") == root_id:
                    fit.samples.setdefault(s["name"], []).append(
                        1e3 * (s["t1"] - s["t0"]))
                elif (s["name"].startswith("serve/stage")
                      and s.get("parent") == device_id):
                    # v16 pipeline stage children: per-stage device walls
                    # under serve/device — what names the bottleneck stage
                    # and prices pipe configs (stage_pctls).
                    fit.samples.setdefault(s["name"], []).append(
                        1e3 * (s["t1"] - s["t0"]))
            fitted += 1
        if fitted == 0:
            raise ModelError(
                f"{path}: no completed serve/request spans with v14 bucket "
                "attrs — cannot fit (pre-v14 recording?)")
        return fitted

    def fit_phase_stats(self, stats: dict, *, model: str | None,
                        bucket: int, precision: str | None,
                        residency: str = "replicated") -> None:
        """Fit from one ``drain_phase_stats()`` aggregate for a known key
        (used when only committed ``per_phase`` bench rows exist)."""
        key = FitKey(model=model, bucket=bucket, precision=precision,
                     residency=residency)
        fit = self._fits.setdefault(key, _KeyFit())
        for name, ent in (stats or {}).items():
            if name in PHASES:
                fit.aggregates[name] = {"count": ent.get("count", 0),
                                        "p50": ent["p50_ms"],
                                        "p99": ent["p99_ms"]}

    @property
    def keys(self) -> list:
        return sorted(self._fits,
                      key=lambda k: (str(k.model), k.bucket,
                                     str(k.precision), k.residency))

    # ------------------------------------------------------------- lookup

    def _pctl(self, key: FitKey, phase: str, q: float) -> float | None:
        fit = self._fits.get(key)
        if fit is None:
            return None
        samples = fit.samples.get(phase)
        if samples:
            return _percentile(sorted(samples), q)
        agg = fit.aggregates.get(phase)
        if agg is not None:
            return agg["p50"] if q <= 0.5 else agg["p99"]
        return None

    def _device_pctl(self, model, bucket: int, precision, residency,
                     q: float) -> tuple:
        """Device percentile for a key, borrowing the nearest fitted bucket
        (linear-in-rows scaling) when this exact bucket was never seen.
        Returns ``(value_ms, note)``."""
        exact = FitKey(model=model, bucket=bucket,
                       precision=precision, residency=residency)
        v = self._pctl(exact, "serve/device", q)
        if v is not None:
            return v, None
        near = [k for k in self._fits
                if (k.model, k.precision, k.residency)
                == (model, precision, residency)
                and self._pctl(k, "serve/device", q) is not None]
        if not near:
            raise ModelError(
                f"nothing fitted for (model={model!r}, precision="
                f"{precision!r}, residency={residency!r}); "
                f"fitted keys: {self.keys}")
        src = min(near, key=lambda k: abs(k.bucket - bucket))
        base = self._pctl(src, "serve/device", q)
        if residency and str(residency).startswith("pipe:"):
            # Pipeline device time is bottleneck-stage bound: extra rows
            # stretch the slowest stage's steady-state work, while the
            # fill/drain ramp stays what the fitted bucket paid — scaling
            # the WHOLE device wall linearly would double-count the ramp.
            stages = self.stage_pctls(model=src.model, bucket=src.bucket,
                                      precision=src.precision,
                                      residency=src.residency, q=q)
            if stages:
                bottleneck = max(stages.values())
                scaled = round(
                    base + bottleneck * (bucket - src.bucket) / src.bucket,
                    3)
                return scaled, (
                    f"bucket {bucket} unseen (pipe): fitted bucket "
                    f"{src.bucket} plus its bottleneck stage scaled in rows")
        scaled = round(base * bucket / src.bucket, 3)
        return scaled, (f"bucket {bucket} unseen: scaled from fitted "
                        f"bucket {src.bucket} linearly in rows")

    def stage_pctls(self, *, model, bucket: int, precision,
                    residency: str, q: float = 0.99) -> dict:
        """Per-stage device percentiles (``serve/stage{i}`` → ms) for one
        fitted pipe key — empty for keys fitted without stage spans. The
        argmax names the bottleneck stage the trace attribution blames."""
        key = FitKey(model=model, bucket=bucket, precision=precision,
                     residency=residency)
        fit = self._fits.get(key)
        if fit is None:
            return {}
        return {
            name: _percentile(sorted(samples), q)
            for name, samples in sorted(fit.samples.items())
            if name.startswith("serve/stage") and samples
        }

    def _host_pctl(self, model, precision, residency, phase: str,
                   q: float) -> float:
        """Bucket-independent host phase (queue/preprocess): pool across
        fitted buckets for the same (model, precision, residency)."""
        vals = [self._pctl(k, phase, q) for k in self._fits
                if (k.model, k.precision, k.residency)
                == (model, precision, residency)]
        vals = [v for v in vals if v is not None]
        if not vals:  # pre-v14 aggregate-only fits may lack the phase
            return 0.0
        return _percentile(sorted(vals), q)

    # ---------------------------------------------------------- prediction

    def predict(self, config: dict, workload: Workload) -> dict:
        """Per-phase p99 estimates for ``config`` under ``workload``.

        ``config`` keys: ``buckets`` (list[int]), ``max_wait_ms``,
        ``hosts``, ``precision``, optional ``residency``.  Multi-model
        workloads predict per tenant and report the request-weighted
        worst phase (the p99 a mixed stream would surface).
        """
        try:
            buckets = sorted(int(b) for b in config["buckets"])
            wait_ms = float(config["max_wait_ms"])
            hosts = int(config["hosts"])
            precision = config.get("precision")
            residency = config.get("residency", "replicated")
        except (KeyError, TypeError, ValueError) as e:
            raise ModelError(f"malformed candidate config {config!r}: {e}")
        if not buckets or hosts < 1:
            raise ModelError(f"malformed candidate config {config!r}")
        models = workload.models or [None]
        # One request = one image row at the front door; the per-request
        # ``rows`` attr is the occupancy of the flush it RODE IN (shared
        # across flush-mates), so it is burstiness evidence below, never
        # an additive rate.
        lam_req = workload.offered_rps
        notes: list = []
        per_model = []
        for m in models:
            share = (1.0 if m is None else
                     sum(1 for r in workload.requests if r.model == m)
                     / max(len(workload.requests), 1))
            lam = lam_req * share
            # Expected flush occupancy: arrivals landing inside one batching
            # window on one host — floored by the MEDIAN recorded flush
            # occupancy, which is direct evidence of burstiness the rate ×
            # window estimate misses — clamped into the candidate's
            # bucket set.
            rows_seen = sorted(r.rows for r in workload.requests
                               if m is None or r.model == m)
            med_rows = rows_seen[len(rows_seen) // 2] if rows_seen else 1
            est_rows = max(1.0, lam * (wait_ms / 1e3) / hosts,
                           float(med_rows))
            bucket = next((b for b in buckets if b >= est_rows), buckets[-1])
            dev_p50, note = self._device_pctl(m, bucket, precision,
                                              residency, 0.50)
            dev_p99, _ = self._device_pctl(m, bucket, precision,
                                           residency, 0.99)
            if note:
                notes.append(f"{m or 'model'}: {note}")
            prep_p50 = self._host_pctl(m, precision, residency,
                                       "serve/preprocess", 0.50)
            prep_p99 = self._host_pctl(m, precision, residency,
                                       "serve/preprocess", 0.99)
            # Fleet service capacity in rows/s: each host turns over one
            # bucket-sized flush per (device + preprocess) service time.
            svc_ms = max(dev_p50 + prep_p50, 1e-3)
            capacity = hosts * bucket * 1e3 / svc_ms
            rho = lam / max(capacity, 1e-9)
            saturated = rho >= 1.0
            if saturated:
                # Finite-burst overflow: the recorded workload is a burst
                # of known duration, so the backlog grows for D seconds
                # and the worst arrival waits backlog/capacity — i.e.
                # D * (rho - 1). Finite, and it ranks (more hosts drain a
                # smaller backlog) where a flat sentinel could not.
                cong_ms = min(1e3 * workload.duration_s * (rho - 1.0),
                              SATURATED_MS)
                notes.append(
                    f"{m or 'model'}: SATURATED (rho={rho:.2f}) — queue "
                    "is the end-of-burst backlog drain")
            else:
                cong_ms = min(dev_p50 * rho / (1.0 - rho), SATURATED_MS)
            queue_p99 = wait_ms + cong_ms
            per_model.append({
                "model": m, "share": round(share, 3),
                "bucket": bucket, "rho": round(rho, 4),
                "saturated": saturated,
                "per_phase": {
                    "serve/queue": round(queue_p99, 3),
                    "serve/preprocess": round(prep_p99, 3),
                    "serve/device": round(dev_p99, 3),
                },
            })
        agg = {ph: max(pm["per_phase"][ph] for pm in per_model)
               for ph in PHASES}
        total = round(sum(agg.values()), 3)
        return {
            "per_phase": {ph: round(v, 3) for ph, v in agg.items()},
            "p99_ms": total,
            "rho": max(pm["rho"] for pm in per_model),
            "saturated": any(pm["saturated"] for pm in per_model),
            "bucket": max(pm["bucket"] for pm in per_model),
            "per_model": per_model,
            "notes": notes,
            "calibration_error_pct": self.calibration_error_pct,
        }

    # --------------------------------------------------------- calibration

    def calibrate(self, predicted: dict, replayed_per_phase: dict, *,
                  window: str = "holdout") -> float:
        """Stamp the calibration error: the relative END-TO-END p99 error
        of ``predicted`` against a replayed measurement — the same
        quantity every downstream claim compares, so the stamp bounds
        exactly what it is quoted for (a per-phase max would be dominated
        by relative error on the smallest phase).  The replayed total is
        the measured ``route/request`` p99 when present, else the sum of
        the measured phase p99s.  Returns the stamped percentage (also
        kept on the model for every later ``predict``)."""
        meas = (replayed_per_phase or {}).get(
            "route/request", {}).get("p99_ms")
        if meas is None:
            vals = [(replayed_per_phase or {}).get(ph, {}).get("p99_ms")
                    for ph in PHASES]
            vals = [v for v in vals if v is not None]
            meas = sum(vals) if vals else None
        pred = predicted.get("p99_ms")
        if not meas or pred is None:
            raise ModelError(
                "calibration needs a predicted p99_ms and replayed phase "
                f"stats (got predicted={sorted(predicted)}, replayed="
                f"{sorted(replayed_per_phase or {})})")
        self.calibration_error_pct = round(
            100.0 * abs(pred - meas) / meas, 1)
        self.calibration_window = window
        return self.calibration_error_pct

    # ------------------------------------------------------------- explain

    def explain(self) -> list:
        lines = [f"latency model: {len(self._fits)} fitted keys"]
        for key in self.keys:
            fit = self._fits[key]
            parts = []
            for ph in PHASES:
                v50 = self._pctl(key, ph, 0.50)
                v99 = self._pctl(key, ph, 0.99)
                if v99 is not None:
                    parts.append(
                        f"{ph.split('/')[1]} p50 {v50:.1f}/p99 {v99:.1f}ms")
            n = sum(len(v) for v in fit.samples.values()) or sum(
                a["count"] for a in fit.aggregates.values())
            lines.append(
                f"  (model={key.model or '-'}, bucket={key.bucket}, "
                f"precision={key.precision or '-'}, "
                f"residency={key.residency}): {'; '.join(parts)} "
                f"[{n} samples]")
        if self.calibration_error_pct is not None:
            lines.append(
                f"  calibration: ±{self.calibration_error_pct:.1f}% "
                f"(vs replay, {self.calibration_window} window)")
        return lines

    def to_record(self) -> dict:
        keys = []
        for key in self.keys:
            ent = {"model": key.model, "bucket": key.bucket,
                   "precision": key.precision, "residency": key.residency,
                   "phases": {}}
            for ph in PHASES:
                v99 = self._pctl(key, ph, 0.99)
                if v99 is not None:
                    ent["phases"][ph] = {
                        "p50_ms": self._pctl(key, ph, 0.50),
                        "p99_ms": v99}
            keys.append(ent)
        return {"keys": keys,
                "calibration_error_pct": self.calibration_error_pct,
                "calibration_window": self.calibration_window}


def fit_from_trace(path: str) -> PhaseLatencyModel:
    model = PhaseLatencyModel()
    model.fit_trace(path)
    return model
