"""Pallas TPU kernel: fused tiny-S attention — scores + softmax + AV in one
VMEM pass per (batch·head) group; fully-fused recompute backward.

Why this op exists (docs/RESULTS.md §4): vit_s16 is the zoo's worst
performer relative to its own roofline — 28.0% MFU against a 44.4% ceiling,
a 1.59× measured/bound gap that the HLO's own cost model localizes to the
per-layer attention block: **31% of modeled time in the softmax chain**
(the [2048, 6, 64, 64] f32 score tensor is 201 MB and the chain touches
several of them per block) and **35% in the score/AV batched matmuls**
(12 288 tiny 64×64×64 matmuls per direction, each filling a quarter of the
128×128 MXU in M×N). The flash kernel (``ops/flash_attention.py``) cannot
help here — measured and rejected at this S in round 3 (4 942 vs 5 722
img/s, ``docs/zoo_flash.json``): its block-tiled online softmax exists to
avoid materializing an S×S tensor that at S=64 is trivially VMEM-sized,
so its per-block state machinery is pure overhead.

This kernel is the flash kernel's tiny-S sibling, purpose-built for the
regime flash loses in (S ≤ 128, Dh ≤ 128 — every per-head score matrix
fits in VMEM whole):

- **Forward**: one grid step per group of ``G`` (batch, head) pairs; q/k/v
  tiles live entirely in VMEM, scores are computed in f32 on the MXU, the
  softmax is a plain (not online) max/exp/sum over the full row, and AV
  lands in the same pass. Nothing between the q/k/v reads and the output
  write ever touches HBM — the 201 MB score tensor and the entire softmax
  chain disappear from the HBM budget.
- **bh-grouping (the MXU-fill lever)**: ``G`` (batch, head) pairs are
  stacked into one [G·S, D] tile and the scores computed as ONE
  [G·S, G·S] matmul with the off-diagonal (cross-head) blocks masked to
  −1e30 before the softmax. Masked probabilities are exactly zero, so the
  AV matmul over the stacked tile is exact with no unstacking. At S=64,
  G=2 turns two quarter-filled 64×64 MXU outputs into one full 128×128
  output (and gives every VPU softmax row 128 full lanes) at the price of
  computing the masked half — the lever the chip A/B decides
  (``MPT_ATTN_BH_BLOCK``; ``tools/bench_attention.py --fused-small``).
- **Backward**: a second single-pass Pallas kernel that RECOMPUTES the
  probabilities in VMEM (one extra q·kᵀ + softmax — tiny-S FLOPs are
  cheap, HBM bytes are not) and emits dq/dk/dv in the same pass:
  dv = pᵀ·do, Δ = Σ_d do·o with o = p·v recomputed in-kernel,
  ds = p·(do·vᵀ − Δ), dq = ds·k·scale, dk = dsᵀ·q·scale. No logsumexp,
  no saved output: the residuals are just the primal q/k/v. The blocked
  XLA backward the flash kernel uses would re-materialize [B·H, S, S]
  probability and ds tensors in HBM — exactly the bytes this kernel
  exists to remove.
- **Masking**: padding (S not a sublane multiple) and the cross-head
  blocks share one precomputed [G·S_pad, G·S_pad] additive f32 bias
  (0 / −1e30), built ONCE in XLA outside the kernel from static shape
  parameters and re-read by every grid step (≤64 KB — VMEM-trivial).
  This keeps every Mosaic-fragile integer div/mod off the kernel body;
  in-kernel there is only dot/exp/max/sum/where, all probed ops. Padded
  q rows softmax over their head's valid keys (l > 0 always) and are
  sliced off by the wrapper; their cotangents are zero because the
  padded ``do`` rows are zero.

Non-TPU backends fall back to ``full_attention`` (identical math — the
reference this kernel is pinned against in
tests/test_fused_attention_small.py via interpret mode), mirroring
``ops/flash_attention.py``'s gating; ``MPT_ATTN_INTERPRET=1`` drives the
real kernel through the Pallas interpreter on CPU (how the tests run it).
Sequences outside the tiny-S envelope (S > 128, or Dh > 128) also take
``full_attention`` — this kernel's domain is exactly the regime where
flash was measured to lose.

Multi-chip: pass ``dp_mesh`` (the training/eval mesh) and the public
wrapper ``shard_map``s the kernel over the mesh's leading (data) axis —
each chip runs the Mosaic call on its own batch shard, identical to the
fused stem / fused eval head contract (ops/fused_stem.py "Multi-chip").
All operands are batch-sharded (no replicated params), so shard_map's
transpose needs no psum and gradients equal the single-call gradients
exactly. Inside an ALREADY shard_map'd context over the same axis (the
``--spmd-mode`` train step) the wrapper detects the bound axis
(``compat.axis_is_manual``) and runs the per-shard call directly.

Trainer integration: ``--attn-impl fused-small`` on the vit family
(models/vit.py) — same function as ``full``/``flash``, different
execution. The measured ship-or-reject A/B is staged in docs/RESULTS.md
§4 (chip window pending), exactly like the §4d stem levers.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG = -1e30  # finite mask value — exp(_NEG - m) underflows to exactly 0

# The tiny-S envelope: one (G·S_pad)² f32 score tile must fit comfortably
# in VMEM and the regime must be the one flash LOSES in (docs/RESULTS.md
# §4: flash wins from S≈2048 up; the crossover is far above this).
MAX_SEQ = 128
MAX_HEAD_DIM = 128


def _bh_block(bh: int, s_pad: int, override: int | None = None) -> int:
    """(batch·head) pairs per grid step. Default fills the 128-lane /
    128×128-MXU tile: G = 128 // S_pad (≥1), reduced until it divides the
    (per-shard) B·H count. ``override`` (the ``bh_block`` kwarg) beats the
    ``MPT_ATTN_BH_BLOCK`` env gate beats the default
    (tools/bench_attention.py --fused-small sweeps them)."""
    raw = os.environ.get("MPT_ATTN_BH_BLOCK")
    if override is not None:
        g = override
    elif raw:
        g = int(raw)
    else:
        g = max(1, 128 // s_pad)
    # VMEM envelope: the kernel holds (G·S_pad)² f32 score/probability
    # tiles; cap G·S_pad at 512 (≤1 MB per tile) so an aggressive override
    # degrades to a buildable grouping instead of a Mosaic compile failure
    # mid-run.
    g = max(1, min(g, bh, max(1, 512 // s_pad)))
    while bh % g:
        g -= 1
    return g


def _mask_bias(g: int, s_pad: int, seq_len: int, causal: bool) -> jnp.ndarray:
    """[G·S_pad, G·S_pad] additive f32 bias: 0 on (same-head, valid-key
    [, causal]) entries, −1e30 elsewhere. Built in XLA from static ints —
    no integer div/mod ever reaches the Mosaic kernel body."""
    r = g * s_pad
    rows = lax.broadcasted_iota(jnp.int32, (r, r), 0)
    cols = lax.broadcasted_iota(jnp.int32, (r, r), 1)
    valid = (rows // s_pad == cols // s_pad) & (cols % s_pad < seq_len)
    if causal:
        valid &= cols % s_pad <= rows % s_pad
    return jnp.where(valid, 0.0, _NEG).astype(jnp.float32)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale):
    q = q_ref[0].astype(jnp.float32) * scale  # [R, D]
    k = k_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) + bias_ref[...]  # [R, R]
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)  # masked entries: exp(_NEG - m) == 0
    l = jnp.sum(p, axis=-1, keepdims=True)  # ≥ 1 valid key per row ⇒ l > 0
    o = lax.dot_general(
        p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (o / l).astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, bias_ref,
                dq_ref, dk_ref, dv_ref, *, scale):
    q = q_ref[0].astype(jnp.float32)  # [R, D]
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    s = lax.dot_general(
        q * scale, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) + bias_ref[...]
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)  # normalized probs [R, R]
    o = lax.dot_general(  # recomputed output — cheaper than an HBM residual
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [R, 1]
    dp = lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # do·vᵀ [R, R]
    ds = p * (dp - delta)
    dq_ref[0] = (lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale).astype(dq_ref.dtype)
    dk_ref[0] = (lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale).astype(dk_ref.dtype)
    dv_ref[0] = lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(dv_ref.dtype)


def _tile_specs(n: int, r: int, d: int):
    """(in_specs for [N, R, D] operands + the shared [R, R] bias, grid)."""
    tile = pl.BlockSpec((1, r, d), lambda i: (i, 0, 0))
    bias = pl.BlockSpec((r, r), lambda i: (0, 0))
    return tile, bias, (n,)


def _fwd_impl(qg, kg, vg, *, seq_len, s_pad, g, causal, interpret):
    n, r, d = qg.shape
    bias = _mask_bias(g, s_pad, seq_len, causal)
    tile, bspec, grid = _tile_specs(n, r, d)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, scale=d**-0.5),
        grid=grid,
        in_specs=[tile, tile, tile, bspec],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((n, r, d), qg.dtype),
        interpret=interpret,
    )(qg, kg, vg, bias)


def _bwd_impl(qg, kg, vg, dog, *, seq_len, s_pad, g, causal, interpret):
    n, r, d = qg.shape
    bias = _mask_bias(g, s_pad, seq_len, causal)
    tile, bspec, grid = _tile_specs(n, r, d)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, scale=d**-0.5),
        grid=grid,
        in_specs=[tile, tile, tile, tile, bspec],
        out_specs=[tile, tile, tile],
        out_shape=[
            jax.ShapeDtypeStruct((n, r, d), qg.dtype),
            jax.ShapeDtypeStruct((n, r, d), kg.dtype),
            jax.ShapeDtypeStruct((n, r, d), vg.dtype),
        ],
        interpret=interpret,
    )(qg, kg, vg, dog, bias)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _attn_grouped(qg, kg, vg, seq_len, s_pad, g, causal, interpret):
    """[N, G·S_pad, D] grouped attention, N = B·H // G."""
    return _fwd_impl(
        qg, kg, vg, seq_len=seq_len, s_pad=s_pad, g=g, causal=causal,
        interpret=interpret,
    )


def _attn_grouped_fwd(qg, kg, vg, seq_len, s_pad, g, causal, interpret):
    out = _fwd_impl(
        qg, kg, vg, seq_len=seq_len, s_pad=s_pad, g=g, causal=causal,
        interpret=interpret,
    )
    return out, (qg, kg, vg)  # probabilities are recomputed, not saved


def _attn_grouped_bwd(seq_len, s_pad, g, causal, interpret, res, dog):
    qg, kg, vg = res
    return _bwd_impl(
        qg, kg, vg, dog, seq_len=seq_len, s_pad=s_pad, g=g, causal=causal,
        interpret=interpret,
    )


_attn_grouped.defvjp(_attn_grouped_fwd, _attn_grouped_bwd)


def _attn_call(q, k, v, *, causal, bh_block, interpret):
    """One (per-shard) kernel invocation over [B, S, H, D] operands."""
    b, s, h, d = q.shape
    # Pad S to the operand dtype's sublane tile: the (1, G·S_pad, D) block's
    # second-minor dim must tile (8, 128) for 4-byte and (16, 128) for
    # 2-byte dtypes — bf16 is the production dtype, and a 56-row bf16 block
    # is exactly the class of chip-only block-spec bug the flash kernel's
    # lse output hit on hardware (docs/RESULTS.md §4c).
    tile = 16 if jnp.dtype(q.dtype).itemsize < 4 else 8
    s_pad = -(-s // tile) * tile
    g = _bh_block(b * h, s_pad, bh_block)

    def to_grouped(x):
        x3 = x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
        if s_pad != s:
            x3 = jnp.pad(x3, ((0, 0), (0, s_pad - s), (0, 0)))
        return x3.reshape(b * h // g, g * s_pad, d)

    outg = _attn_grouped(
        to_grouped(q), to_grouped(k), to_grouped(v), s, s_pad, g, causal,
        interpret,
    )
    out3 = outg.reshape(b * h, s_pad, d)[:, :s]
    return out3.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def fused_attention_small(
    q, k, v, *, causal: bool = False, bh_block: int | None = None,
    interpret: bool | None = None, dp_mesh=None,
) -> jnp.ndarray:
    """Fused tiny-S attention over [B, S, H, D] inputs (the repo layout).

    Domain: S ≤ 128, head dim ≤ 128 — the regime where the flash kernel's
    block machinery was measured to LOSE to plain XLA (docs/RESULTS.md §4,
    round 3) and the [B, H, S, S] softmax chain is the byte cost. Outside
    the envelope the call degrades to ``full_attention`` (identical math).

    ``bh_block``: (batch·head) pairs fused per grid step (None = auto /
    ``MPT_ATTN_BH_BLOCK`` — see module docstring, bh-grouping).

    ``interpret``: None = Pallas on TPU, ``full_attention`` fallback
    elsewhere (or the Pallas interpreter when ``MPT_ATTN_INTERPRET`` is
    set — how tests drive the real kernel path on CPU); True forces the
    interpreter; False forces the compiled kernel.

    ``dp_mesh``: training/eval mesh. With >1 device on its leading (data)
    axis the call is ``shard_map``-partitioned over that axis — each
    device runs the Mosaic call on its batch shard (a Mosaic custom call
    has no GSPMD partitioning rule of its own). If the axis is ALREADY
    bound (the spmd-mode step's shard_map), the per-shard call runs
    directly — no nesting."""
    from mpi_pytorch_tpu.ops.ring_attention import full_attention
    from mpi_pytorch_tpu.utils.env import env_flag
    from mpi_pytorch_tpu.utils.hardware import tpu_backend

    b, s, h, d = q.shape
    n_data = 1
    if dp_mesh is not None:
        from mpi_pytorch_tpu.parallel.compat import axis_is_manual

        axis = dp_mesh.axis_names[0]
        if not axis_is_manual(axis):
            n_data = dp_mesh.shape[axis]
    if s > MAX_SEQ or d > MAX_HEAD_DIM or (n_data > 1 and b % n_data):
        # Outside the tiny-S envelope (flash/full own that regime), or a
        # batch that does not tile the data axis (replicating the Mosaic
        # call would be strictly worse than XLA's partitioned path).
        return full_attention(q, k, v, causal=causal)
    if interpret is None:
        if env_flag("MPT_ATTN_INTERPRET"):
            interpret = True
        elif not tpu_backend():
            return full_attention(q, k, v, causal=causal)
        else:
            interpret = False

    call = functools.partial(
        _attn_call, causal=causal, bh_block=bh_block, interpret=interpret
    )
    if n_data > 1:
        from jax.sharding import PartitionSpec as P

        from mpi_pytorch_tpu.parallel.compat import shard_map

        axis = dp_mesh.axis_names[0]
        return shard_map(
            call,
            mesh=dp_mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=P(axis),
            check_vma=False,
        )(q, k, v)
    return call(q, k, v)
