"""Binary framed persistent serving transport (ISSUE 16).

The HTTP path ships every request as ``.npy``-over-POST on a FRESH TCP
connection and long-polls the result back as another request — at
millions-of-users load the per-request cost is connection setup +
headers + an extra buffer copy per hop, none of it chip time (ROADMAP
open item 3). This module is the fast data plane: a length-prefixed
binary frame codec carried over a SMALL POOL of persistent connections
per (client, host) pair, with request pipelining and out-of-order
response matching by ``req_id`` — one multiplexed stream instead of two
HTTP round-trips per request.

Frame layout (little-endian, ``docs/SERVING.md`` has the full spec)::

    prefix  : magic b"MPTW" | version u8 | ftype u8 | flags u16
              | req_id u64 | header_len u32 | payload_len u32   (24 B)
    header  : per-ftype binary struct (below) — never JSON, never base64
    payload : raw array bytes (C-order), exactly payload_len

Frame types: SUBMIT (array header + image bytes), RESULT (array header
+ top-k int32 bytes), ERROR (typed-failure header: the PR 12 taxonomy
as a u16 kind + detail + retry_after_ms — the 429 hint rides the wire),
CANCEL (hedge-loser revocation, header/payload empty), PING/PONG
(handshake + liveness). Array headers carry dtype token, shape, model
id, and the W3C traceparent, so multi-tenancy (ISSUE 14) and
distributed tracing (ISSUE 13) survive the transport switch intact.

Decode failures are TYPED and immediate — a truncated, malformed,
oversized, or version-skewed frame raises (never hangs, never resyncs:
a framing error poisons the stream, so the connection is torn down and
its in-flight requests fail host-shaped, which the router re-dispatches).

``WireListener`` is the server half mounted next to the HTTP surface by
``serve/host.py`` (the port rides the readiness file as ``wire_port``);
``WireClient`` is the client half under ``serve/client.py``'s
``WireHost``. Both are host-only (no jax) and unit-tested against fake
peers in ``tests/test_wire.py``.

Chaos: ``maybe_fault_wire_delay()`` honors ``MPT_FAULT_WIRE_DELAY_MS``
(+ ``_HOST`` scope + ``_JITTER_MS``) on the server's response path —
a deterministic slow wire on one host, the lever the hedge drill uses.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from concurrent.futures import CancelledError, Future

import numpy as np

from mpi_pytorch_tpu.serve.batcher import (
    HostUnavailableError,
    ModelNotResidentError,
    PreprocessError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    UnknownModelError,
)
from mpi_pytorch_tpu.utils.env import env_int

MAGIC = b"MPTW"
WIRE_VERSION = 1

# Frame types.
SUBMIT = 1
RESULT = 2
ERROR = 3
CANCEL = 4
PING = 5
PONG = 6
_FRAME_TYPES = frozenset((SUBMIT, RESULT, ERROR, CANCEL, PING, PONG))

# prefix: magic | version | ftype | flags | req_id | header_len | payload_len
PREFIX = struct.Struct("<4sBBHQII")
PREFIX_LEN = PREFIX.size  # 24

# Caps: a frame is read fully into memory before dispatch, so both halves
# are bounded — an oversized length field is rejected from the PREFIX
# alone (no allocation happens first).
MAX_HEADER_BYTES = 64 * 1024
MAX_PAYLOAD_BYTES = 64 * 1024 * 1024

# Wire dtype tokens: the closed set of array dtypes the serving wire
# carries (request pixels + top-k results). Closed on purpose — an
# unknown token is a malformed frame, not a pickle.
_DTYPE_BY_TOKEN = {
    1: np.dtype(np.uint8),
    2: np.dtype(np.int8),
    3: np.dtype(np.int16),
    4: np.dtype(np.int32),
    5: np.dtype(np.int64),
    6: np.dtype(np.float16),
    7: np.dtype(np.float32),
    8: np.dtype(np.float64),
    9: np.dtype(np.bool_),
}
_TOKEN_BY_DTYPE = {dt.str: tok for tok, dt in _DTYPE_BY_TOKEN.items()}

# ERROR-frame kinds: the PR 12 failure taxonomy as wire enums. The
# client maps each back to the EXACT typed exception, so the router's
# request-shaped-vs-host-shaped dispatch logic needs no transport
# special-casing.
ERR_QUEUE_FULL = 1
ERR_CLOSED = 2
ERR_UNKNOWN_MODEL = 3
ERR_NOT_RESIDENT = 4
ERR_PREPROCESS = 5
ERR_REQUEST = 6  # generic request-shaped ServeError
ERR_INTERNAL = 7  # host-shaped: anything non-ServeError server-side
ERR_CANCELLED = 8

_ERR_CLASSES = {
    ERR_CLOSED: ServerClosedError,
    ERR_UNKNOWN_MODEL: UnknownModelError,
    ERR_NOT_RESIDENT: ModelNotResidentError,
    ERR_PREPROCESS: PreprocessError,
    ERR_REQUEST: ServeError,
    ERR_INTERNAL: HostUnavailableError,
}


class WireError(ServeError):
    """Base class for framing errors. A framing error is CONNECTION
    poison: after one, stream offsets are untrusted, so the peer must
    tear the connection down (in-flight requests fail host-shaped and
    the router re-dispatches them)."""


class MalformedFrameError(WireError):
    """Bad magic, unknown frame type / dtype token, or a header whose
    contents do not parse — the stream is not (or no longer) MPTW."""


class FrameTooLargeError(WireError):
    """A length field exceeds the header/payload cap. Rejected from the
    prefix alone, BEFORE any allocation."""


class WireVersionError(WireError):
    """Peer speaks a different MPTW version — refuse loudly instead of
    misparsing a future layout."""


class TruncatedFrameError(WireError):
    """The stream ended mid-frame (peer died / short read) — distinct
    from malformed: the bytes were fine, there were just too few."""


# --------------------------------------------------------------------------
# codec (pure, host-only, unit-testable)
# --------------------------------------------------------------------------


def encode_frame(ftype: int, req_id: int, header: bytes = b"",
                 payload: bytes = b"") -> bytes:
    """One wire frame as bytes (prefix + header + payload)."""
    if ftype not in _FRAME_TYPES:
        raise MalformedFrameError(f"unknown frame type {ftype}")
    if len(header) > MAX_HEADER_BYTES or len(payload) > MAX_PAYLOAD_BYTES:
        raise FrameTooLargeError(
            f"frame over cap (header {len(header)} B, payload "
            f"{len(payload)} B; caps {MAX_HEADER_BYTES}/{MAX_PAYLOAD_BYTES})"
        )
    return (
        PREFIX.pack(MAGIC, WIRE_VERSION, ftype, 0, req_id,
                    len(header), len(payload))
        + header + payload
    )


def decode_prefix(buf: bytes) -> tuple[int, int, int, int]:
    """(ftype, req_id, header_len, payload_len) from a 24-byte prefix.
    Every refusal is typed: truncation, bad magic, version skew,
    unknown type, over-cap lengths."""
    if len(buf) < PREFIX_LEN:
        raise TruncatedFrameError(
            f"prefix truncated ({len(buf)}/{PREFIX_LEN} bytes)"
        )
    magic, version, ftype, _flags, req_id, hlen, plen = PREFIX.unpack(
        buf[:PREFIX_LEN]
    )
    if magic != MAGIC:
        raise MalformedFrameError(f"bad magic {magic!r} (want {MAGIC!r})")
    if version != WIRE_VERSION:
        raise WireVersionError(
            f"peer speaks MPTW v{version}, this end v{WIRE_VERSION}"
        )
    if ftype not in _FRAME_TYPES:
        raise MalformedFrameError(f"unknown frame type {ftype}")
    if hlen > MAX_HEADER_BYTES or plen > MAX_PAYLOAD_BYTES:
        raise FrameTooLargeError(
            f"declared lengths over cap (header {hlen} B, payload {plen} B)"
        )
    return ftype, req_id, hlen, plen


def pack_array_header(arr: np.ndarray, model: str | None = None,
                      traceparent: str | None = None) -> bytes:
    """SUBMIT/RESULT header: dtype token, shape, model id, traceparent."""
    token = _TOKEN_BY_DTYPE.get(arr.dtype.str)
    if token is None:
        raise MalformedFrameError(
            f"dtype {arr.dtype} is not a wire dtype "
            f"(supported: {sorted(str(d) for d in _DTYPE_BY_TOKEN.values())})"
        )
    parts = [struct.pack("<BB", token, arr.ndim),
             struct.pack(f"<{arr.ndim}I", *arr.shape)]
    for s in (model or "", traceparent or ""):
        b = s.encode("utf-8")
        parts.append(struct.pack("<H", len(b)) + b)
    return b"".join(parts)


def unpack_array_header(header: bytes) -> tuple[np.dtype, tuple, str | None,
                                                str | None]:
    """(dtype, shape, model, traceparent) from an array header."""
    try:
        token, ndim = struct.unpack_from("<BB", header, 0)
        shape = struct.unpack_from(f"<{ndim}I", header, 2)
        off = 2 + 4 * ndim
        strs = []
        for _ in range(2):
            (n,) = struct.unpack_from("<H", header, off)
            off += 2
            if off + n > len(header):
                raise struct.error("string past header end")
            strs.append(header[off:off + n].decode("utf-8"))
            off += n
    except (struct.error, UnicodeDecodeError) as e:
        raise MalformedFrameError(f"unparseable array header: {e}") from None
    dtype = _DTYPE_BY_TOKEN.get(token)
    if dtype is None:
        raise MalformedFrameError(f"unknown dtype token {token}")
    return dtype, shape, strs[0] or None, strs[1] or None


def decode_array(header: bytes, payload: bytes | memoryview) -> tuple[
        np.ndarray, str | None, str | None]:
    """(array, model, traceparent) from an array frame. The array is a
    VIEW over the received payload buffer — the zero-copy contract: the
    server's batch loop copies it once, straight into the padded bucket
    slot ``device_put`` consumes."""
    dtype, shape, model, trace = unpack_array_header(header)
    want = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize
    if len(payload) != want:
        raise MalformedFrameError(
            f"payload is {len(payload)} B but dtype {dtype} shape "
            f"{tuple(shape)} needs {want} B"
        )
    arr = np.frombuffer(payload, dtype=dtype).reshape(shape)
    return arr, model, trace


def encode_error_header(kind: int, detail: str,
                        retry_after_ms: float | None = None,
                        model: str | None = None) -> bytes:
    parts = [struct.pack(
        "<Hd", kind,
        float("nan") if retry_after_ms is None else float(retry_after_ms),
    )]
    for s in (detail, model or ""):
        b = s.encode("utf-8")[:2048]
        parts.append(struct.pack("<H", len(b)) + b)
    return b"".join(parts)


def decode_error_header(header: bytes) -> tuple[int, str, float | None,
                                                str | None]:
    """(kind, detail, retry_after_ms, model) from an ERROR header."""
    try:
        kind, retry = struct.unpack_from("<Hd", header, 0)
        off = 10
        strs = []
        for _ in range(2):
            (n,) = struct.unpack_from("<H", header, off)
            off += 2
            if off + n > len(header):
                raise struct.error("string past header end")
            strs.append(header[off:off + n].decode("utf-8"))
            off += n
    except (struct.error, UnicodeDecodeError) as e:
        raise MalformedFrameError(f"unparseable error header: {e}") from None
    return (kind, strs[0], None if retry != retry else retry,
            strs[1] or None)


def exception_to_error_header(exc: BaseException) -> bytes:
    """The PR 12 taxonomy → ERROR header, typed hints included (the 429's
    retry_after_ms and rejected-model ride as fields, not prose)."""
    if isinstance(exc, QueueFullError):
        return encode_error_header(ERR_QUEUE_FULL, str(exc),
                                   exc.retry_after_ms, exc.model)
    if isinstance(exc, ServerClosedError):
        return encode_error_header(ERR_CLOSED, str(exc))
    if isinstance(exc, UnknownModelError):
        return encode_error_header(ERR_UNKNOWN_MODEL, str(exc))
    if isinstance(exc, ModelNotResidentError):
        return encode_error_header(ERR_NOT_RESIDENT, str(exc))
    if isinstance(exc, PreprocessError):
        return encode_error_header(ERR_PREPROCESS, str(exc))
    if isinstance(exc, CancelledError):
        return encode_error_header(ERR_CANCELLED, "request cancelled")
    if isinstance(exc, ServeError):
        return encode_error_header(ERR_REQUEST, str(exc))
    return encode_error_header(
        ERR_INTERNAL, f"{type(exc).__name__}: {exc}"
    )


def error_header_to_exception(header: bytes) -> BaseException:
    """ERROR header → the exact typed exception the in-process path
    would have raised (the transport must not blur the taxonomy)."""
    kind, detail, retry_after_ms, model = decode_error_header(header)
    if kind == ERR_QUEUE_FULL:
        return QueueFullError(detail, retry_after_ms=retry_after_ms,
                              model=model)
    if kind == ERR_CANCELLED:
        return CancelledError(detail)
    cls = _ERR_CLASSES.get(kind)
    if cls is None:
        raise MalformedFrameError(f"unknown error kind {kind}")
    return cls(detail)


# --------------------------------------------------------------------------
# framed stream I/O
# --------------------------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Exactly ``n`` bytes from ``sock``, or TruncatedFrameError on EOF
    mid-read (a clean EOF at a frame BOUNDARY is signalled by the
    zero-byte first read — callers treat n_read == 0 as peer-closed)."""
    if n == 0:
        return b""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            if got == 0:
                raise ConnectionResetError("peer closed")
            raise TruncatedFrameError(
                f"stream ended mid-frame ({got}/{n} bytes)"
            )
        got += r
    return bytes(buf)


def read_frame(sock: socket.socket) -> tuple[int, int, bytes, bytes]:
    """The next (ftype, req_id, header, payload) off ``sock``. Raises
    ConnectionResetError on a clean peer close at a frame boundary, a
    typed WireError on anything else."""
    ftype, req_id, hlen, plen = decode_prefix(_recv_exact(sock, PREFIX_LEN))
    header = _recv_exact(sock, hlen) if hlen else b""
    payload = _recv_exact(sock, plen) if plen else b""
    return ftype, req_id, header, payload


# --------------------------------------------------------------------------
# chaos: deterministic slow wire (ISSUE 16 satellite)
# --------------------------------------------------------------------------


def maybe_fault_wire_delay(host_index: int) -> float:
    """Sleep on the response path when the ``MPT_FAULT_WIRE_DELAY_MS``
    gate targets this host (``MPT_FAULT_WIRE_DELAY_HOST``; unset/-1 =
    every host), plus an optional bounded jitter
    (``MPT_FAULT_WIRE_DELAY_JITTER_MS``, deterministic per-call phase so
    a drill's delay profile replays). Returns the ms slept (0 = gate
    cold) so call sites can stamp fault records."""
    delay_ms = env_int("MPT_FAULT_WIRE_DELAY_MS", 0)
    if delay_ms <= 0:
        return 0.0
    target = env_int("MPT_FAULT_WIRE_DELAY_HOST", -1)
    if target >= 0 and target != host_index:
        return 0.0
    jitter = env_int("MPT_FAULT_WIRE_DELAY_JITTER_MS", 0)
    if jitter > 0:
        # Deterministic phase: a counter-derived triangle wave, not a
        # PRNG — the same drill sleeps the same schedule every run.
        with _jitter_lock:
            global _jitter_phase
            _jitter_phase = (_jitter_phase + 1) % (2 * jitter)
            delay_ms += abs(jitter - _jitter_phase)
    time.sleep(delay_ms / 1e3)
    return float(delay_ms)


_jitter_phase = 0
_jitter_lock = threading.Lock()


# --------------------------------------------------------------------------
# server half
# --------------------------------------------------------------------------


class _ConnWriter:
    """Per-connection outbound frame queue + dedicated writer thread.

    Result delivery is decoupled from result PRODUCTION: a future's
    done-callback (which runs on the server's single completion loop)
    only encodes and enqueues — the blocking ``sendall`` (and the chaos
    fault-gate sleep) happen here, so one client with a stalled TCP
    window stalls only its own connection, never the completion loop or
    any other connection. The queue is bounded: a client too slow to
    drain ``maxsize`` result frames is a laggard, and its connection is
    torn down rather than buffered without bound (the reader loop wakes
    on the shutdown and fails its in-flight requests host-shaped, which
    the router re-dispatches)."""

    def __init__(self, conn: socket.socket, host_index: int,
                 maxsize: int = 256):
        self._conn = conn
        self._host_index = host_index
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self.dead = False
        self._thread = threading.Thread(
            target=self._loop, name="wire-writer", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            frame, fault = self._q.get()
            if frame is None or self.dead:
                return
            if fault:
                maybe_fault_wire_delay(self._host_index)
            try:
                self._conn.sendall(frame)
            except OSError:
                self.dead = True
                return  # peer gone; the reader loop handles cleanup

    def send(self, frame: bytes, *, fault: bool = False) -> None:
        """Enqueue a frame (never blocks). ``fault=True`` applies the
        chaos wire-delay gate on the writer thread before the write —
        the response-path semantics the hedge drill depends on."""
        if self.dead:
            return
        try:
            self._q.put_nowait((frame, fault))
        except queue.Full:
            # Laggard client: maxsize undrained frames deep. Tear the
            # connection down; the reader loop notices and cleans up.
            self.dead = True
            try:
                self._conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self, drain_s: float = 0.5) -> None:
        """Stop the writer after a bounded best-effort drain of frames
        already queued (the final ERROR frame on a poisoned connection
        rides this). A writer stuck in ``sendall`` is unblocked by the
        caller's socket shutdown right after."""
        try:
            self._q.put_nowait((None, False))
        except queue.Full:
            pass  # stalled writer; the dead flag + shutdown end it
        self._thread.join(timeout=drain_s)
        self.dead = True


class WireListener:
    """The serving host's framed wire surface: accept persistent
    connections, decode SUBMIT frames straight into the request path,
    and write RESULT/ERROR frames back out of order as futures land.

    ``submit_fn(image, model, trace) -> Future`` is the only coupling to
    the serving stack (``serve/host.py`` binds it to the real server's
    submit; tests bind a fake). ``trace`` is the raw traceparent string
    — parsing it is the submit_fn's business, same as the HTTP header
    path. CANCEL frames call ``Future.cancel()`` on the pending future:
    a request the batch loop has not yet assembled is revoked before it
    can occupy a batch slot (the hedge-loser contract)."""

    def __init__(self, submit_fn, *, host_index: int = -1, port: int = 0,
                 logger=None):
        self._submit_fn = submit_fn
        self._host_index = host_index
        self._logger = logger
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind(("127.0.0.1", port))
        self._lsock.listen(32)
        self.port = self._lsock.getsockname()[1]
        self._closed = False
        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="wire-accept", daemon=True
        )
        self._accept_thread.start()

    # -- connection handling

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._lsock.accept()
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name="wire-conn", daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        writer = _ConnWriter(conn, self._host_index)
        pending: dict[int, Future] = {}
        pend_lock = threading.Lock()
        try:
            while True:
                try:
                    ftype, req_id, header, payload = read_frame(conn)
                except ConnectionResetError:
                    return  # peer closed cleanly between frames
                except WireError as e:
                    # Framing error = connection poison: refuse loudly
                    # once (best effort), then tear down.
                    if self._logger is not None:
                        self._logger.warning("wire: dropping conn: %s", e)
                    writer.send(encode_frame(
                        ERROR, 0, exception_to_error_header(e)))
                    return
                if ftype == PING:
                    writer.send(encode_frame(PONG, req_id))
                elif ftype == CANCEL:
                    with pend_lock:
                        fut = pending.get(req_id)
                    if fut is not None:
                        fut.cancel()
                elif ftype == SUBMIT:
                    self._handle_submit(writer, pending, pend_lock,
                                        req_id, header, payload)
                # RESULT/ERROR/PONG from a client are ignored: this end
                # only ever receives SUBMIT/CANCEL/PING.
        except OSError:
            return
        finally:
            with self._lock:
                self._conns.discard(conn)
            writer.close()
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
            # In-flight futures whose connection died: nobody is left to
            # receive the result — cancel so the batch loop can skip.
            # Snapshot-and-clear under the lock, cancel OUTSIDE it:
            # Future.cancel() on a pending future runs _done
            # synchronously, and _done's first statement takes pend_lock.
            with pend_lock:
                futs = list(pending.values())
                pending.clear()
            for fut in futs:
                fut.cancel()

    def _handle_submit(self, writer, pending, pend_lock,
                       req_id, header, payload) -> None:
        try:
            image, model, trace = decode_array(header, payload)
        except WireError as e:
            self._reply_error(writer, req_id, e)
            return
        try:
            fut = self._submit_fn(image, model, trace)
        except BaseException as e:  # typed admission rejection (429/503/…)
            self._reply_error(writer, req_id, e)
            return
        with pend_lock:
            pending[req_id] = fut

        def _done(f: Future, rid=req_id) -> None:
            # Runs on whatever thread resolves the future — the server's
            # SINGLE completion loop. Only encode + enqueue here; the
            # blocking socket write (and the chaos fault sleep) happen on
            # this connection's writer thread, so a stalled client never
            # head-of-line-blocks other requests or connections.
            with pend_lock:
                pending.pop(rid, None)
            if f.cancelled():
                self._reply_error(writer, rid, CancelledError(), fault=True)
                return
            exc = f.exception()
            if exc is not None:
                self._reply_error(writer, rid, exc, fault=True)
                return
            result = np.ascontiguousarray(f.result())
            writer.send(encode_frame(
                RESULT, rid, pack_array_header(result),
                result.tobytes()), fault=True)

        fut.add_done_callback(_done)

    def _reply_error(self, writer, req_id, exc, *, fault=False) -> None:
        writer.send(encode_frame(
            ERROR, req_id, exception_to_error_header(exc)), fault=fault)

    def close(self) -> None:
        self._closed = True
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# --------------------------------------------------------------------------
# client half
# --------------------------------------------------------------------------


class _WireConn:
    """One persistent connection: a send lock (pipelined writers race)
    and a reader thread matching RESULT/ERROR frames to futures by
    req_id (out-of-order completion is the POINT of the framed wire —
    a slow request never head-of-line-blocks the stream)."""

    def __init__(self, host: str, port: int, timeout_s: float):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)  # reader blocks; liveness is PING's job
        self.send_lock = threading.Lock()
        self.inflight: dict[int, Future] = {}
        self.inflight_lock = threading.Lock()
        self.dead = False
        self.reader = threading.Thread(
            target=self._read_loop, name="wire-reader", daemon=True
        )
        self.reader.start()

    def _read_loop(self) -> None:
        err: BaseException = HostUnavailableError("wire connection lost")
        try:
            while True:
                ftype, req_id, header, payload = read_frame(self.sock)
                if ftype == RESULT:
                    fut = self._pop(req_id)
                    if fut is not None:
                        try:
                            arr, _model, _trace = decode_array(
                                header, payload)
                        except WireError as e:
                            fut.set_exception(e)
                        else:
                            # Copy: the recv buffer is reused per frame
                            # read, the result outlives it. Results are
                            # top-k index rows — tiny.
                            fut.set_result(np.array(arr))
                elif ftype == ERROR:
                    fut = self._pop(req_id)
                    if fut is not None:
                        fut.set_exception(error_header_to_exception(header))
                elif ftype == PONG:
                    fut = self._pop(req_id)
                    if fut is not None:
                        fut.set_result(True)
        except ConnectionResetError:
            pass  # server closed between frames
        except WireError as e:
            err = e
        except OSError as e:
            err = HostUnavailableError(f"wire read failed: {e}")
        finally:
            self.dead = True
            try:
                self.sock.close()
            except OSError:
                pass
            with self.inflight_lock:
                flights, self.inflight = dict(self.inflight), {}
            for fut in flights.values():
                if not fut.done():
                    fut.set_exception(
                        err if isinstance(err, ServeError)
                        else HostUnavailableError(str(err))
                    )

    def _pop(self, req_id: int) -> Future | None:
        with self.inflight_lock:
            return self.inflight.pop(req_id, None)

    def send(self, frame: bytes) -> None:
        with self.send_lock:
            self.sock.sendall(frame)

    def close(self) -> None:
        self.dead = True
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class WireClient:
    """The client half: a small pool of persistent connections to ONE
    host, pipelined submits fanned across them round-robin, responses
    matched by req_id. Reconnect-on-stale: a dead connection's in-flight
    futures fail host-shaped (the router's re-dispatch food) and the
    slot is re-dialed on next use."""

    def __init__(self, host: str, port: int, *, pool: int = 2,
                 connect_timeout_s: float = 2.0):
        self._host = host
        self._port = port
        self._timeout = connect_timeout_s
        self._conns: list[_WireConn | None] = [None] * max(1, int(pool))
        self._lock = threading.Lock()
        self._next_id = 0
        self._next_conn = 0
        self._closed = False

    def _req_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _conn(self) -> _WireConn:
        with self._lock:
            if self._closed:
                raise ServerClosedError("wire client closed")
            i = self._next_conn % len(self._conns)
            self._next_conn += 1
            c = self._conns[i]
            if c is None or c.dead:
                try:
                    c = _WireConn(self._host, self._port, self._timeout)
                except OSError as e:
                    raise HostUnavailableError(
                        f"wire connect to {self._host}:{self._port} "
                        f"failed: {e}"
                    ) from None
                self._conns[i] = c
            return c

    def submit(self, image: np.ndarray, *, model: str | None = None,
               traceparent: str | None = None) -> tuple[int, Future]:
        """Pipeline one request; returns (req_id, Future). The future
        lands a top-k int32 array, a typed ServeError, or cancellation.
        req_id is the CANCEL handle."""
        image = np.ascontiguousarray(image)
        req_id = self._req_id()
        frame = encode_frame(
            SUBMIT, req_id, pack_array_header(image, model, traceparent),
            image.tobytes(),
        )
        conn = self._conn()
        fut: Future = Future()
        fut.set_running_or_notify_cancel()  # cancel() rides CANCEL frames
        with conn.inflight_lock:
            conn.inflight[req_id] = fut
        try:
            conn.send(frame)
        except OSError as e:
            with conn.inflight_lock:
                conn.inflight.pop(req_id, None)
            conn.dead = True
            raise HostUnavailableError(f"wire submit failed: {e}") from None
        return req_id, fut

    def cancel(self, req_id: int) -> None:
        """Best-effort CANCEL frame for ``req_id`` (the hedge-loser
        revocation). Sent on every live pooled connection — CANCEL is
        idempotent and an unknown req_id is a no-op server-side, so
        over-delivery is free and under-delivery (a dead conn) is
        already handled by that conn's teardown cancelling its
        in-flight futures."""
        frame = encode_frame(CANCEL, req_id)
        with self._lock:
            conns = [c for c in self._conns if c is not None and not c.dead]
        for c in conns:
            try:
                c.send(frame)
            except OSError:
                pass

    def ping(self, timeout_s: float = 2.0) -> bool:
        """Handshake/liveness: PING → PONG round-trip on one pooled
        connection (dials it if needed)."""
        req_id = self._req_id()
        conn = self._conn()
        fut: Future = Future()
        with conn.inflight_lock:
            conn.inflight[req_id] = fut
        conn.send(encode_frame(PING, req_id))
        return bool(fut.result(timeout=timeout_s))

    def close(self) -> None:
        with self._lock:
            self._closed = True
            conns, self._conns = list(self._conns), [None]
        for c in conns:
            if c is not None:
                c.close()
