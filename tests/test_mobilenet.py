"""MobileNetV2: torchvision-exact parameter count, forward shape, the
depthwise/inverted-residual structure, and a loss-decreasing train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.models import create_model_bundle

# The whole module rides the expensive session-scoped model-zoo
# compile (or end-to-end trainer runs): core-suite runs skip it
# (pytest -m 'not slow').
pytestmark = pytest.mark.slow


def test_mobilenet_param_count_matches_torchvision():
    """3,504,872 params at 1000 classes — torchvision mobilenet_v2's exact
    count (BN running stats live in batch_stats, not params, matching
    torch's buffer/parameter split)."""
    bundle, variables = create_model_bundle(
        "mobilenet_v2", 1000, rng=jax.random.PRNGKey(0), image_size=64
    )
    got = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert got == 3_504_872


def test_mobilenet_forward_and_structure():
    bundle, variables = create_model_bundle(
        "mobilenet_v2", 10, rng=jax.random.PRNGKey(0), image_size=64
    )
    params = variables["params"]
    # 17 inverted-residual blocks; block0 (expand=1) has no expand conv.
    assert sum(1 for k in params if k.startswith("block")) == 17
    assert "expand" not in params["block0"] and "expand" in params["block1"]
    # Depthwise kernel: [3, 3, 1, hidden] (one filter per channel).
    assert params["block1"]["depthwise"]["kernel"].shape == (3, 3, 1, 96)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    logits = bundle.model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_mobilenet_trains_through_standard_step():
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step

    bundle, variables = create_model_bundle(
        "mobilenet_v2", 10, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
    )
    rng = np.random.default_rng(2)
    images = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    step = make_train_step(jnp.float32)
    losses = []
    for _ in range(3):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert state.batch_stats is not None  # BN model: running stats updated


def test_mobilenet_pretrained_errors(tmp_path):
    """mobilenet_v2 IS convertible (torch_mapping has its rules), so
    use_pretrained with no converted file must point at the converter via
    FileNotFoundError — while a genuinely unconvertible family (the ViTs,
    which have no torchvision-checkpoint counterpart in this zoo) still
    gets the direct random-init ValueError."""
    import pytest

    with pytest.raises(FileNotFoundError, match="convert_torchvision"):
        create_model_bundle(
            "mobilenet_v2", 10, use_pretrained=True,
            rng=jax.random.PRNGKey(0), image_size=32,
            pretrained_dir=str(tmp_path),
        )
    with pytest.raises(ValueError, match="random init"):
        create_model_bundle(
            "vit_s16", 10, use_pretrained=True,
            rng=jax.random.PRNGKey(0), image_size=32,
            pretrained_dir=str(tmp_path),
        )
