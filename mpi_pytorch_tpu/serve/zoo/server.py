"""ZooServer: one serving HOST holding many model TENANTS (ISSUE 14).

The multi-tenant generalization of ``InferenceServer``: each resident
tenant runs its own full serving pipeline (bounded queue, dynamic
batcher, preprocess pool, per-tenant metrics registry, per-tenant
executable sets from the shared ``ZooExecutablePool``) over the SAME
device mesh — so a batcher flush is single-tenant BY CONSTRUCTION (a
coalesced batch only ever holds one model's requests; a mixed flush
would need a cross-model executable that doesn't exist), and per-tenant
admission is the tenant's own bounded queue plus the fleet router's
per-tenant front-door budget.

Residency is dynamic — the cold-model swap-in state machine::

    ensure_model(m):  plan (evict LRU idle tenants until the packing
                      budget fits) → pool.ensure (load + warm-probe,
                      zoo/pool.py) → activate (stand the tenant server)
                      → bump facts_generation → kind="fleet"
                      event="swap_in" record, packing plan stamped
    evict_model(m):   drain the tenant server → release the pool sets →
                      bump facts_generation → event="evict" record

``facts_generation`` is the cache-coherence satellite: a host's resident
model set is advertised through ``/healthz``/``/metricsz``, and a
remote probe caches those facts — the generation counter lets the
``RemoteHost`` facts cache invalidate the instant a swap-in/evict
changes the set, so the router never dispatches a tenant to a host that
just evicted it.
"""

from __future__ import annotations

import itertools
import threading
import time

from mpi_pytorch_tpu.serve.batcher import (
    ModelNotResidentError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    UnknownModelError,
)
from mpi_pytorch_tpu.serve.fleet.router import LocalHost
from mpi_pytorch_tpu.serve.zoo.pool import ZooExecutablePool
from mpi_pytorch_tpu.serve.zoo.registry import ModelRegistry


class ZooServer:
    """N tenants' serving pipelines over one replica's chips."""

    def __init__(
        self,
        cfg,
        *,
        registry: ModelRegistry | None = None,
        pool: ZooExecutablePool | None = None,
        metrics=None,
        host_index: int | None = None,
        load_checkpoint: bool = True,
        mesh=None,
        logger=None,
        canary=None,
        drift=None,
    ):
        from mpi_pytorch_tpu.obs.context import SpanRecorder
        from mpi_pytorch_tpu.utils.logging import MetricsWriter, run_logger

        self.cfg = cfg
        self._logger = logger or run_logger()
        # Quality gate + drift feed (ISSUE 19): the fleet-shared
        # ``obs.CanaryGate`` every mutation on this host consults (swap-in
        # after the warm probe, set_precision, convert_residency) and the
        # shared ``obs.DriftMonitor`` each tenant server's completion loop
        # feeds top-1 predictions. Both default None — single-host zoo
        # callers keep v14 behavior exactly.
        self._canary = canary
        self._drift = drift
        self.registry = registry or ModelRegistry.from_config(cfg)
        self.pool = pool if pool is not None else ZooExecutablePool(
            cfg, self.registry, mesh=mesh, load_checkpoint=load_checkpoint,
            logger=self._logger,
        )
        self.host_index = host_index
        self.name = "serve" if host_index is None else f"h{host_index}"
        self._metrics = metrics or MetricsWriter(cfg.metrics_file)
        self._owns_metrics = metrics is None
        # One shared span ring: the host's /tracez is a single cursor
        # space across every tenant's request spans.
        self._spans = SpanRecorder()
        self.start_ts = time.time()
        self._snapshot_seq = itertools.count()
        budget_mb = float(getattr(cfg, "serve_pack_budget_mb", 0.0) or 0.0)
        self._budget_bytes = int(budget_mb * 1024 * 1024) or None
        self._lock = threading.Lock()  # tenant map / LRU / generation
        self._swap_lock = threading.Lock()  # serializes swap-in/evict
        self._tenants: dict[str, object] = {}  # model -> InferenceServer
        self._last_used: dict[str, float] = {}
        self._generation = 0
        self._closed = False

        startup = [s.model for s in self.registry.specs() if not s.cold]
        if not startup:
            raise ServeError(
                "a zoo host needs at least one non-cold tenant at startup "
                "(every spec marked :cold would leave the host serving "
                "nothing)"
            )
        # The STARTUP packing plan: the non-cold residents must fit
        # together with nothing to evict — over budget here is a spec
        # error, rejected loudly with the plan's arithmetic.
        plan = self._plan_with(startup)
        if not plan.fits:
            from mpi_pytorch_tpu.serve.zoo.registry import PackingError

            raise PackingError(
                "startup tenant set exceeds the packing budget (nothing "
                "is evictable at startup). " + plan.explain()
            )
        try:
            for model in startup:
                self._activate(model, event=None)  # startup: no record
        except BaseException:
            self.close(drain=False)
            raise
        self._logger.info(
            "zoo[%s]: %d resident tenant(s) %s (registered %s)\n%s",
            self.name, len(self._tenants), sorted(self._tenants),
            sorted(self.registry.models()), plan.explain(),
        )

    # ------------------------------------------------------------ residency

    @property
    def facts_generation(self) -> int:
        return self._generation

    @property
    def closed(self) -> bool:
        return self._closed

    def models(self) -> tuple[str, ...]:
        """The RESIDENT tenant set — what this host advertises for
        routing (``/healthz`` facts; the registered set may be larger)."""
        with self._lock:
            return tuple(sorted(self._tenants))

    def registered_models(self) -> tuple[str, ...]:
        return tuple(sorted(self.registry.models()))

    def tenant(self, model: str):
        """The tenant's ``InferenceServer``; typed errors for unknown vs
        evicted tenants (the router re-routes only the latter)."""
        self.registry.spec(model)  # UnknownModelError for non-tenants
        with self._lock:
            srv = self._tenants.get(model)
        if srv is None:
            raise ModelNotResidentError(
                f"model {model!r} is not resident on {self.name} "
                f"(resident: {sorted(self._tenants)}); cold-load it via "
                "ensure_model"
            )
        return srv

    def tenants(self) -> dict:
        with self._lock:
            return dict(self._tenants)

    def _plan_with(self, models) -> object:
        # n_devices + residencies unlock the planner's third option
        # (shard:K) and gate measured bytes on the layout they were
        # measured at (ISSUE 17).
        return self.registry.plan_packing(
            models, self._budget_bytes,
            measured=self.pool.measured_bytes(),
            n_devices=int(self.pool.mesh.devices.size),
            residencies=self.pool.residencies(),
        )

    def _activate(self, model: str, event: str | None = "swap_in") -> None:
        """Load → warm-probe → activate one tenant (the cold swap-in when
        ``event`` is set; the startup build when None)."""
        from mpi_pytorch_tpu.serve.server import InferenceServer

        with self._swap_lock:
            with self._lock:
                if model in self._tenants:
                    return
                resident = list(self._tenants)
            # LRU eviction under the packing budget: evict the least
            # recently USED resident tenant until the plan fits (the
            # incoming tenant is never the victim; PackingError from
            # plan_packing if it can never fit even alone).
            while True:
                plan = self._plan_with(resident + [model])
                if plan.fits:
                    break
                with self._lock:
                    evictable = sorted(
                        (m for m in resident),
                        key=lambda m: self._last_used.get(m, 0.0),
                    )
                if not evictable:
                    from mpi_pytorch_tpu.serve.zoo.registry import PackingError

                    raise PackingError(
                        f"cannot fit tenant {model!r}: nothing left to "
                        "evict. " + plan.explain()
                    )
                victim = evictable[0]
                self._evict_locked_out(victim, reason=f"lru for {model}")
                resident.remove(victim)
            # The plan may have picked the THIRD residency option —
            # shard:K — for the incoming tenant and/or for already-
            # resident ones (shard beats evict). Apply resident
            # conversions first so their freed bytes exist before the
            # new build lands.
            from mpi_pytorch_tpu.serve.sharding import parse_residency

            for other in resident:
                entry = plan.entry(other)
                if entry is not None and (
                    entry.residency != self.pool.residency(other)
                ):
                    self._convert_locked(
                        other, entry.residency,
                        reason=f"pack for {model}", plan=plan,
                    )
            entry = plan.entry(model)
            want = parse_residency(entry.residency if entry else None)
            sets = self.pool.ensure(model, residency=want)  # load + warm-probe
            # Mutation-gate order (ISSUE 19): warm probe → canary →
            # activate. The zero-compile warm probe proved the sets can
            # serve; the canary verdict says whether the TENANT should —
            # a FAIL latched before eviction blocks the re-swap-in (the
            # pinned references outlive residency for exactly this).
            verdict = None
            if event is not None and self._canary is not None:
                try:
                    verdict = self._canary.check(model, mutation="swap_in")
                except Exception:
                    self.pool.release(model)  # no orphaned pool sets
                    raise
            tenant_cfg = self.registry.tenant_cfg(model)
            srv = InferenceServer(
                tenant_cfg, executables=sets, metrics=self._metrics,
                host_index=self.host_index, model=model, spans=self._spans,
                drift=self._drift,
            )
            with self._lock:
                self._tenants[model] = srv
                self._last_used[model] = time.monotonic()
                self._generation += 1
                resident_now = sorted(self._tenants)
            if event is not None:
                self._logger.info(
                    "zoo[%s]: cold swap-in of %s complete (resident %s)\n%s",
                    self.name, model, resident_now, plan.explain(),
                )
                record = {
                    "kind": "fleet", "event": event,
                    "host": self.name, "model": model,
                    "resident": resident_now,
                    "compiles_after_warmup": srv.compiles_after_warmup(),
                    "plan": plan.to_record(),
                }
                if verdict is not None:
                    # Schema-v15: the canary verdict this mutation passed
                    # under — absent without a gate, so v14 streams stay
                    # byte-identical.
                    record["canary_verdict"] = verdict
                res = self.pool.residency(model)
                if res != "replicated":
                    # A sharded swap-in crossed topologies on the way in:
                    # say so, with the bytes the reshard actually moved
                    # (schema v13).
                    record["residency"] = res
                    record["shard_degree"] = srv.shard_degree
                    record["reshard_bytes"] = sum(
                        int(e.reshard_stats.bytes_moved)
                        for e in sets.values()
                        if getattr(e, "reshard_stats", None) is not None
                    )
                self._metrics.write(record)

    def _convert_locked(
        self, model: str, residency, reason: str, plan=None,
    ) -> None:
        """Live residency conversion (``_swap_lock`` held): reshard the
        pool sets through the bounded per-leaf path, stand a NEW tenant
        server over the rebuilt executables, swap it in atomically, then
        drain the old one — in-flight requests on the old server finish,
        and a submit racing the swap retries once (``submit``). A failed
        conversion (``ColdSwapError``) propagates with the OLD sets still
        live and zero-compile."""
        from mpi_pytorch_tpu.serve.server import InferenceServer

        res_str = residency if isinstance(residency, str) else str(residency)
        if self.pool.residency(model) == res_str:
            return
        new_sets, reshard_bytes = self.pool.reshard(model, res_str)
        tenant_cfg = self.registry.tenant_cfg(model)
        srv = InferenceServer(
            tenant_cfg, executables=new_sets, metrics=self._metrics,
            host_index=self.host_index, model=model, spans=self._spans,
            drift=self._drift,
        )
        with self._lock:
            old = self._tenants.get(model)
            self._tenants[model] = srv
            self._generation += 1
        if old is not None:
            old.close(drain=True)
        self._logger.info(
            "zoo[%s]: converted tenant %s to %s (%s; %.1f MB moved)",
            self.name, model, res_str, reason, reshard_bytes / 1e6,
        )
        record = {
            "kind": "fleet", "event": "retune",
            "host": self.name, "model": model,
            "residency": res_str,
            "shard_degree": srv.shard_degree,
            "reshard_bytes": int(reshard_bytes),
            "compiles_after_warmup": srv.compiles_after_warmup(),
            "detail": reason,
        }
        if res_str.startswith("pipe:"):
            # Schema-v16: a conversion TO pipe says how it was cut and
            # what each flush will pay in inter-stage traffic (summed
            # ledger-booked per-hop bytes at full micro-batch count).
            exe = next(iter(new_sets.values()))
            record["pipe_stages"] = int(res_str.split(":")[1])
            record["interstage_bytes"] = int(
                getattr(exe, "interstage_bytes_per_flush", lambda: 0)()
            )
        if self._canary is not None:
            record["canary_verdict"] = self._canary.verdict(model)
        if plan is not None:
            record["plan"] = plan.to_record()
        self._metrics.write(record)

    def convert_residency(self, model: str, residency, *,
                          reason: str = "operator") -> None:
        """Operator/planner entry point: convert a RESIDENT tenant's
        weight layout live (replicated↔tp:K↔fsdp:K↔pipe:K)."""
        if self._closed:
            raise ServeError(f"zoo host {self.name} is shut down")
        self.registry.spec(model)
        self.tenant(model)  # ModelNotResidentError for non-residents
        if self._canary is not None:
            # Gated mutation (ISSUE 19): resharding a tenant that is
            # answering wrong destroys the evidence — refuse until the
            # canary recovers (CanaryBlockedError, refusal on the record).
            self._canary.check(model, mutation=f"convert_residency:{residency}")
        with self._swap_lock:
            self._convert_locked(model, residency, reason=reason)

    def set_pack_budget_mb(self, mb: float) -> None:
        """Live packing-budget squeeze: re-plan the current residents at
        the new budget and apply what the plan picked — residency
        conversions FIRST (shard beats evict), LRU eviction only if the
        plan still cannot fit every resident sharded."""
        with self._swap_lock:
            self._budget_bytes = int(float(mb) * 1024 * 1024) or None
            with self._lock:
                resident = list(self._tenants)
            while True:
                plan = self._plan_with(resident)
                for m in resident:
                    entry = plan.entry(m)
                    if entry is not None and (
                        entry.residency != self.pool.residency(m)
                    ):
                        self._convert_locked(
                            m, entry.residency,
                            reason="pack budget", plan=plan,
                        )
                if plan.fits or len(resident) <= 1:
                    # A single over-budget resident stays up: serving
                    # degraded beats serving nothing (the startup gate
                    # already rejected truly impossible specs).
                    break
                with self._lock:
                    evictable = sorted(
                        resident,
                        key=lambda m: self._last_used.get(m, 0.0),
                    )
                victim = evictable[0]
                self._evict_locked_out(victim, reason="pack budget")
                resident.remove(victim)

    def ensure_model(self, model: str) -> None:
        """Cold swap-in (idempotent): make ``model`` resident here —
        load from the persistent compilation cache, warm-probe, activate
        (``zoo/pool.py``'s gate: a set that would compile under traffic
        never activates)."""
        if self._closed:
            raise ServeError(f"zoo host {self.name} is shut down")
        self.registry.spec(model)
        self._activate(model, event="swap_in")

    def _evict_locked_out(self, model: str, reason: str) -> None:
        """Evict one tenant (``_swap_lock`` held by the caller): drain
        its server, release its pool sets, bump the facts generation."""
        with self._lock:
            srv = self._tenants.pop(model, None)
            if srv is None:
                return
            self._last_used.pop(model, None)
            self._generation += 1
            resident_now = sorted(self._tenants)
        srv.close(drain=True)
        self.pool.release(model)
        self._logger.info(
            "zoo[%s]: evicted tenant %s (%s; resident %s)",
            self.name, model, reason, resident_now,
        )
        self._metrics.write({
            "kind": "fleet", "event": "evict",
            "host": self.name, "model": model,
            "detail": reason, "resident": resident_now,
        })

    def evict_model(self, model: str) -> None:
        self.registry.spec(model)
        with self._swap_lock:
            self._evict_locked_out(model, reason="operator evict")

    # ---------------------------------------------------------- request path

    def submit(self, image, model: str | None = None, trace=None,
               shadow: bool = False):
        """Enqueue one request for ``model``. The tenant must be named
        on a multi-tenant host (a single-tenant zoo defaults to its one
        tenant); rejections carry the tenant on the typed error.
        ``shadow=True`` marks a canary probe (ISSUE 19): real path,
        excluded from SLO/admission/billing counters."""
        if model is None:
            registered = self.registry.models()
            if len(registered) != 1:
                raise UnknownModelError(
                    "a multi-tenant host needs model= on every request "
                    f"(tenants: {sorted(registered)})"
                )
            model = registered[0]
        for attempt in range(2):
            srv = self.tenant(model)
            with self._lock:
                self._last_used[model] = time.monotonic()
            try:
                if trace is not None or shadow:
                    return srv.submit(image, trace=trace, shadow=shadow)
                return srv.submit(image)
            except QueueFullError as e:
                e.model = model  # the typed rejection names its tenant
                raise
            except ServerClosedError:
                # A live residency conversion swapped the tenant server
                # between our lookup and the enqueue — the new server is
                # already in the map; retry once. Only a host-level
                # shutdown re-raises (zero lost requests through a
                # conversion is the dryrun leg's assertion).
                if attempt or self._closed:
                    raise
        raise AssertionError("unreachable")  # pragma: no cover

    def predict_batch(self, images, model: str | None = None,
                      timeout: float | None = None):
        import numpy as np

        futs = [self.submit(im, model=model) for im in images]
        return np.stack([f.result(timeout=timeout) for f in futs])

    # ------------------------------------------------------------- telemetry

    def stats(self) -> dict:
        """Host-level counters + the per-tenant breakdown."""
        tenants = {m: s.stats() for m, s in self.tenants().items()}
        out = {
            "served": sum(s["served"] for s in tenants.values()),
            "rejected": sum(s["rejected"] for s in tenants.values()),
            "failed": sum(s["failed"] for s in tenants.values()),
            "padded_rows": sum(s["padded_rows"] for s in tenants.values()),
            "queue_depth": sum(s["queue_depth"] for s in tenants.values()),
            "compiles_after_warmup": self.compiles_after_warmup(),
            "models": tenants,
            "facts_generation": self.facts_generation,
        }
        return out

    def tenant_stats(self) -> dict:
        """model → its tenant server's stats (bench/CI per-tenant
        columns)."""
        return {m: s.stats() for m, s in self.tenants().items()}

    def registry_snapshot(self) -> dict:
        """The host-level snapshot the router scores and the collector
        scrapes: counters/queue-depth summed across tenants, histogram
        summaries folded conservatively (count/sum summed, percentiles
        MAX — "the worst tenant's tail", which is what the autoscaler's
        worst-host p99 wants), plus the per-tenant snapshots under
        ``models`` and the ``facts_generation`` for remote facts-cache
        invalidation."""
        snaps = {m: s.registry_snapshot() for m, s in self.tenants().items()}
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        for snap in snaps.values():
            for k, v in snap.get("counters", {}).items():
                counters[k] = counters.get(k, 0.0) + (v or 0.0)
            for k, v in snap.get("gauges", {}).items():
                if v is None:
                    gauges.setdefault(k, None)
                elif k in ("serve/queue_depth", "serve/compiles_after_warmup"):
                    gauges[k] = (gauges.get(k) or 0.0) + v
                else:
                    gauges[k] = max(gauges.get(k) or 0.0, v)
            for k, h in snap.get("histograms", {}).items():
                if not h:
                    continue
                if k not in hists:
                    hists[k] = dict(h)
                    continue
                agg = hists[k]
                agg["count"] = agg.get("count", 0) + h.get("count", 0)
                agg["sum"] = agg.get("sum", 0.0) + h.get("sum", 0.0)
                for q in ("p50", "p95", "p99", "max"):
                    if h.get(q) is not None:
                        agg[q] = max(agg.get(q) or 0.0, h[q])
        return {
            "counters": counters, "gauges": gauges, "histograms": hists,
            "models": snaps,
            "facts_generation": self.facts_generation,
            "seq": next(self._snapshot_seq),
            "start_ts": self.start_ts,
        }

    def traces(self, since: int = 0) -> dict:
        """The shared span ring (one cursor space across tenants)."""
        return self._spans.export(since)

    def compiles_after_warmup(self) -> int:
        """Steady-state compiles over EVERY pool set — an inactive
        tenant's compile is just as much a broken invariant."""
        return self.pool.compiles_after_warmup()

    def _healthz(self) -> dict:
        stats = self.stats()
        first = next(iter(self.tenants().values()), None)
        return {
            "status": "ok" if not self._closed else "closing",
            "queue_depth": stats["queue_depth"],
            "compiles_after_warmup": stats["compiles_after_warmup"],
            "served": stats["served"],
            "rejected": stats["rejected"],
            # The multi-model facts (ISSUE 14): the resident set IS a
            # routing fact, and the generation counter is what keeps a
            # remote probe's facts cache coherent through swap-ins.
            "models": list(self.models()),
            "registered_models": list(self.registered_models()),
            # model → residency: a sharded tenant is one logical host
            # occupying K chips — the router's facts must say so.
            "residency": {
                m: self.pool.residency(m) for m in self.models()
            },
            "facts_generation": self.facts_generation,
            "queue_capacity": self.queue_capacity,
            "max_wait_ms": first.max_wait_ms if first else None,
            "active_buckets": list(first.active_buckets) if first else [],
            "buckets": list(first.buckets) if first else [],
            "precisions": list(first.precisions) if first else [],
            "parity_top1": first.parity_top1 if first else None,
            "topk": first.topk if first else None,
            "host_index": self.host_index,
            "pid": __import__("os").getpid(),
            "time": time.time(),
            "start_ts": self.start_ts,
        }

    # --------------------------------------------------------------- control

    @property
    def precision(self) -> str:
        """The active precision of the first tenant (bench sweep surface;
        tenants may diverge under per-tenant controller retunes)."""
        first = next(iter(self.tenants().values()), None)
        return first.precision if first else "bf16"

    @property
    def parity_top1(self):
        first = next(iter(self.tenants().values()), None)
        return first.parity_top1 if first else None

    @property
    def queue_capacity(self) -> int:
        """Admission capacity this host contributes to the fleet budget:
        one tenant queue per REGISTERED tenant (stable across swap-ins —
        the router's auto budget must not drift with residency)."""
        return self.cfg.serve_queue_depth * max(1, len(self.registry.models()))

    def _fanout(self, model, fn) -> None:
        targets = (
            [self.tenant(model)] if model is not None
            else list(self.tenants().values())
        )
        for srv in targets:
            fn(srv)

    def set_max_wait_ms(self, v: float, model: str | None = None) -> None:
        self._fanout(model, lambda s: s.set_max_wait_ms(v))

    def set_active_buckets(self, buckets, model: str | None = None) -> None:
        self._fanout(model, lambda s: s.set_active_buckets(buckets))

    def set_precision(self, precision: str, model: str | None = None) -> None:
        if self._canary is not None:
            # Gated mutation (ISSUE 19): checked per targeted tenant
            # BEFORE any server switches — a fanout must be all-or-none
            # (a half-switched precision fleet is its own incident).
            targets = (
                [model] if model is not None else sorted(self.tenants())
            )
            for m in targets:
                self._canary.check(m, mutation=f"set_precision:{precision}")
        self._fanout(model, lambda s: s.set_precision(precision))

    # ------------------------------------------------------------- lifecycle

    def close(self, drain: bool = True) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            tenants = dict(self._tenants)
        for srv in tenants.values():
            try:
                srv.close(drain=drain)
            except Exception as e:  # noqa: BLE001 — close the rest
                self._logger.warning("zoo tenant close failed: %s", e)
        if self._owns_metrics:
            try:
                self._metrics.close()
            except Exception as e:  # noqa: BLE001
                self._logger.warning("zoo metrics close failed: %s", e)

    def __enter__(self) -> "ZooServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class TenantHandle:
    """One (host, tenant) pair as a controller-facing unit: the AIMD
    retune knobs (max_wait / active buckets / precision) of exactly one
    tenant on exactly one host — what makes controller retunes act PER
    TENANT (the retune record carries ``host`` + ``model``)."""

    def __init__(self, host_name: str, model: str, server):
        self.host_name = host_name
        self.model = model
        self.name = f"{host_name}/{model}"  # unique controller key
        self._server = server

    def snapshot(self) -> dict:
        return self._server.registry_snapshot()

    @property
    def max_wait_ms(self) -> float:
        return self._server.max_wait_ms

    @property
    def active_buckets(self):
        return self._server.active_buckets

    @property
    def buckets(self):
        return self._server.buckets

    @property
    def precision(self) -> str:
        return self._server.precision

    @property
    def precisions(self):
        return self._server.precisions

    @property
    def parity_top1(self):
        return self._server.parity_top1

    @property
    def residency(self) -> str:
        return getattr(self._server, "residency", "replicated")

    @property
    def shard_degree(self) -> int:
        return int(getattr(self._server, "shard_degree", 1))

    def set_max_wait_ms(self, v: float) -> None:
        self._server.set_max_wait_ms(v)

    def set_active_buckets(self, buckets) -> None:
        self._server.set_active_buckets(buckets)

    def set_precision(self, precision: str) -> None:
        self._server.set_precision(precision)

    def compiles_after_warmup(self) -> int:
        return self._server.compiles_after_warmup()


class ZooHost(LocalHost):
    """``HostHandle`` over an in-process ``ZooServer`` — the LocalHost
    twin with the multi-model surface the router and controller read:
    resident ``models()``, ``ensure_model`` (the router's cold-load
    spill), and per-tenant ``tenants()`` units for the controller."""

    def __init__(self, server: ZooServer):
        self.server = server
        self.name = server.name
        self.index = server.host_index

    def submit(self, image, trace=None, model=None, shadow=False):
        return self.server.submit(
            image, model=model, trace=trace, shadow=shadow
        )

    def models(self):
        return self.server.models()

    def ensure_model(self, model: str) -> None:
        self.server.ensure_model(model)

    def evict_model(self, model: str) -> None:
        self.server.evict_model(model)

    def residency(self, model: str) -> str:
        """The tenant's weight layout — "replicated" or "tp:K"/"fsdp:K"
        (a sharded tenant occupies K chips of this host's mesh)."""
        return self.server.pool.residency(model)

    def convert_residency(self, model: str, residency, *,
                          reason: str = "operator") -> None:
        self.server.convert_residency(model, residency, reason=reason)

    @property
    def facts_generation(self) -> int:
        return self.server.facts_generation

    def tenants(self) -> list[TenantHandle]:
        return [
            TenantHandle(self.name, model, srv)
            for model, srv in sorted(self.server.tenants().items())
        ]

    def alive(self) -> bool:
        return not self.server.closed

    def qsize(self) -> int:
        return self.server.stats()["queue_depth"]

    @property
    def queue_capacity(self) -> int:
        return self.server.queue_capacity

    @property
    def buckets(self):
        first = next(iter(self.server.tenants().values()), None)
        return tuple(first.buckets) if first else ()

    @property
    def active_buckets(self):
        first = next(iter(self.server.tenants().values()), None)
        return tuple(first.active_buckets) if first else ()

    @property
    def max_wait_ms(self) -> float:
        first = next(iter(self.server.tenants().values()), None)
        return first.max_wait_ms if first else 0.0

    def set_max_wait_ms(self, v: float) -> None:
        self.server.set_max_wait_ms(v)

    def set_active_buckets(self, buckets) -> None:
        self.server.set_active_buckets(buckets)

    @property
    def precision(self) -> str:
        first = next(iter(self.server.tenants().values()), None)
        return first.precision if first else "bf16"

    @property
    def precisions(self):
        first = next(iter(self.server.tenants().values()), None)
        return tuple(first.precisions) if first else ()

    def set_precision(self, precision: str) -> None:
        self.server.set_precision(precision)

    @property
    def parity_top1(self):
        first = next(iter(self.server.tenants().values()), None)
        return first.parity_top1 if first else None
