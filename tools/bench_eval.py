"""Inference/evaluation throughput: batched sharded forward, images/sec/chip.

The reference's second driver is a 4-stage MPI inference pipeline whose
predict stage runs ONE image per forward per rank
(``evaluation_pipeline.py:132-159``). This framework collapses it into one
jitted batched forward over all chips (``evaluate.py``); this tool measures
that forward with the harness shared with ``tools/bench_zoo.py`` and prints
one JSON line per batch size.

Timing note: the eval step outputs only scalars, and scalar futures can
resolve early through the remote-PJRT relay (see bench.py). The timed loop
therefore chains every step's metrics into one on-device accumulator and
blocks on that — the final value depends on every step, so it cannot be
ready before the work is.

Run: ``python tools/bench_eval.py [--model resnet18] [--batches 256,1024,4096]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from bench_zoo import NUM_CLASSES, build_state_and_batch  # noqa: E402


def bench_eval(model_name: str, batch_per_chip: int, image: int, steps: int, warmup: int):
    from mpi_pytorch_tpu.train.step import make_eval_step
    from mpi_pytorch_tpu.utils.hardware import peak_bf16_tflops, step_flops

    mesh, state, device_batch, n_chips, batch = build_state_and_batch(
        model_name, batch_per_chip, image, optimizer=False
    )
    eval_step = make_eval_step(jnp.bfloat16)
    compiled = eval_step.lower(state, device_batch).compile()
    flops = step_flops(compiled)

    add = jax.jit(lambda acc, m: acc + m["loss"] + m["count"])

    acc = jnp.zeros((), jnp.float32)
    for _ in range(warmup + 1):  # +1 so the accumulator add is compiled too
        acc = add(acc, compiled(state, device_batch))
    jax.block_until_ready(acc)

    acc = jnp.zeros((), jnp.float32)
    t0 = time.perf_counter()
    for _ in range(steps):
        acc = add(acc, compiled(state, device_batch))
    jax.block_until_ready(acc)  # depends on every step above
    dt = time.perf_counter() - t0

    ips = steps * batch / dt
    tflops_per_chip = flops * steps / dt / 1e12  # cost analysis is per-device
    peak = peak_bf16_tflops(jax.devices()[0])
    rec = {
        "metric": f"eval images/sec/chip (bf16, {NUM_CLASSES} classes, {image}px)",
        "model": model_name,
        "batch_per_chip": batch_per_chip,
        "chips": n_chips,
        "images_per_sec_per_chip": round(ips / n_chips, 1),
        "step_ms": round(dt / steps * 1e3, 2),
        "tflops_per_chip": round(tflops_per_chip, 2),
    }
    if peak and flops > 0:
        rec["mfu_pct"] = round(100.0 * tflops_per_chip / peak, 1)
    return rec


def bench_head(batch: int, d: int, steps: int, warmup: int):
    """A/B of the PREDICTIONS-PASS head stage in isolation (the eval path's
    [B, 64 500] logits question — VERDICT r4 item 5): the XLA composition
    (bf16 matmul → pinned-f32 logits → CE + argmax, what
    evaluate._make_predict_step runs today) vs ``ops.fused_head_ce.
    head_predict`` (one VMEM-streaming kernel, no [B, V] tensor). Chained
    on-device accumulator barrier, same as bench_eval."""
    import numpy as np

    from mpi_pytorch_tpu.ops.fused_head_ce import head_predict
    from mpi_pytorch_tpu.train.step import metrics_from_logits

    rng = np.random.default_rng(0)
    feats = jnp.asarray(rng.normal(size=(batch, d)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(d, NUM_CLASSES)) * 0.05, jnp.float32)
    b = jnp.asarray(rng.normal(size=(NUM_CLASSES,)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(batch,)), jnp.int32)

    from jax import lax

    # w/b travel as ARGUMENTS: a 132 MB closure constant gets baked into
    # the remote-compile request body, which the relay rejects (HTTP
    # 413/500 — same failure mode as bench_stem's first version).
    @jax.jit
    def xla_head(feats, labels, w, b):
        logits = feats @ w.astype(jnp.bfloat16) + b.astype(jnp.bfloat16)
        logits = lax.optimization_barrier(logits.astype(jnp.float32))
        m = metrics_from_logits(logits, labels)
        preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return m["loss"] + jnp.sum(preds)

    @jax.jit
    def fused_head(feats, labels, w, b):
        loss, preds = head_predict(feats, w, b, labels)
        return jnp.sum(loss) + jnp.sum(preds)

    out = []
    from mpi_pytorch_tpu.ops.fused_head_ce import PREDICT_MAX_ROWS, _predict_row_block

    # Row tiling (round 6): beyond PREDICT_MAX_ROWS the kernel streams
    # ≤1024-row blocks through a (rows, vocab) grid instead of falling
    # back — so B=4096 is now a real fused measurement, labeled with its
    # row-block size.
    rb = _predict_row_block(batch)
    fused_label = (
        "fused" if batch <= PREDICT_MAX_ROWS
        else (f"fused(row-tiled rb={rb})" if rb else "fused(untileable: xla fallback)")
    )
    for label, fn in (("xla", xla_head), (fused_label, fused_head)):
        add = jax.jit(lambda acc, v: acc + v)
        acc = jnp.zeros((), jnp.float32)
        for _ in range(warmup + 1):
            acc = add(acc, fn(feats, labels, w, b))
        float(acc)  # value fetch: block_until_ready lies here (§4c)
        acc = jnp.zeros((), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(steps):
            acc = add(acc, fn(feats, labels, w, b))
        float(acc)  # a fetched value cannot be fabricated
        dt = time.perf_counter() - t0
        out.append({
            "metric": f"predictions head ms (B={batch}, D={d}, V={NUM_CLASSES})",
            "head": label,
            "step_ms": round(dt / steps * 1e3, 3),
        })
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--batches", default="256,1024,4096")
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--head", action="store_true",
                    help="A/B the isolated predictions-pass head stage "
                    "(XLA vs ops.fused_head_ce.head_predict) per batch size")
    args = ap.parse_args()
    for b in (x.strip() for x in args.batches.split(",") if x.strip()):
        try:
            if args.head:
                for rec in bench_head(int(b), 512, args.steps, args.warmup):
                    print(json.dumps(rec), flush=True)
                continue
            rec = bench_eval(args.model, int(b), args.image, args.steps, args.warmup)
        except Exception as e:
            rec = {"model": args.model, "batch_per_chip": int(b),
                   "error": f"{type(e).__name__}: {e}"[:300]}
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
