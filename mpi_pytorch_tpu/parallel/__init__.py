from mpi_pytorch_tpu.parallel import collectives
from mpi_pytorch_tpu.parallel.mesh import (
    batch_spec,
    create_mesh,
    named_shardings,
    param_specs,
    replicated,
    shard_batch,
)

__all__ = [
    "batch_spec",
    "collectives",
    "create_mesh",
    "named_shardings",
    "param_specs",
    "replicated",
    "shard_batch",
]
