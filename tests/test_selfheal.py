"""Self-healing training (ISSUE 10): exact-step mid-epoch resume (the data
cursor in the checkpoint's topology sidecar + loader fast-forward) and the
bad-step policies (--bad-step-policy skip|rollback), plus the decode-failure
quarantine path in data/pipeline.py — all on the 8-virtual-device CPU mesh.

The tentpole pin: a run preempted MID-epoch (deterministically, via the
MPT_FAULT_PREEMPT_AT_STEP gate) saves a dirty checkpoint whose cursor lets
auto-resume continue at step N+1 with ZERO replayed optimizer steps — the
resumed run's final parameters equal the uninterrupted run's bit-for-bit
(the save is exact f32 and the walk is deterministic)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu import checkpoint as ckpt
from mpi_pytorch_tpu.config import Config
from mpi_pytorch_tpu.data.manifest import Manifest, manifest_fingerprint
from mpi_pytorch_tpu.data.pipeline import BadSampleLimitError, DataLoader
from mpi_pytorch_tpu.train import elastic
from mpi_pytorch_tpu.utils.env import FAULT_GATES, reset_fault_counters


class FakeMetrics:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))


@pytest.fixture
def clean_gates():
    reset_fault_counters()
    yield
    for name in FAULT_GATES:
        os.environ.pop(name, None)
    reset_fault_counters()


def _synthetic_manifest(n=20):
    return Manifest(
        filenames=tuple(f"f{i}.jpg" for i in range(n)),
        labels=(np.arange(n) % 7).astype(np.int32),
        category_ids=np.arange(n),
        img_dir="unused",
    )


# ---------------------------------------------------------------------------
# cursor fast-forward on all three data paths
# ---------------------------------------------------------------------------


def _loader_batches(dl, epoch, start):
    return [(i.copy(), l.copy()) for i, l in dl.epoch(epoch, start_batch=start)]


@pytest.mark.parametrize("start", [0, 1, 3])
def test_fastforward_streaming_matches_full_tail(start):
    m = _synthetic_manifest(20)
    kw = dict(batch_size=4, image_size=(8, 8), shuffle=True, seed=3,
              synthetic=True, num_workers=2)
    full = _loader_batches(DataLoader(m, **kw), 1, 0)
    ff = _loader_batches(DataLoader(m, **kw), 1, start)
    assert len(ff) == len(full) - start
    for (fi, fl), (gi, gl) in zip(full[start:], ff):
        np.testing.assert_array_equal(fi, gi)
        np.testing.assert_array_equal(fl, gl)


def test_fastforward_ram_cache_and_filling_epoch():
    m = _synthetic_manifest(16)
    kw = dict(batch_size=4, image_size=(8, 8), shuffle=True, seed=0,
              synthetic=True, host_cache=True, num_workers=2)
    ref = DataLoader(m, **kw)
    full0 = _loader_batches(ref, 0, 0)
    # Filling epoch with a fast-forward start: the skipped prefix is
    # backfilled, and the yielded tail matches the full walk's tail.
    dl = DataLoader(m, **kw)
    ff0 = _loader_batches(dl, 0, 2)
    for (fi, fl), (gi, gl) in zip(full0[2:], ff0):
        np.testing.assert_array_equal(fi, gi)
        np.testing.assert_array_equal(fl, gl)
    assert dl.wait_cache_complete()
    # Cached epoch (the fast slice path) honors start_batch too.
    full1 = _loader_batches(ref, 1, 0)
    ff1 = _loader_batches(dl, 1, 3)
    for (fi, fl), (gi, gl) in zip(full1[3:], ff1):
        np.testing.assert_array_equal(fi, gi)
        np.testing.assert_array_equal(fl, gl)


def test_fastforward_packed_mmap(tmp_path):
    from mpi_pytorch_tpu.data.packed import write_pack

    m = _synthetic_manifest(16)
    packed_dir = str(tmp_path / "packed")
    write_pack(m, (8, 8), f"{packed_dir}/train_8x8", synthetic=True,
               num_workers=2)
    kw = dict(batch_size=4, image_size=(8, 8), shuffle=True, seed=1,
              synthetic=True, packed_dir=packed_dir, num_workers=2)
    full = _loader_batches(DataLoader(m, **kw), 2, 0)
    ff = _loader_batches(DataLoader(m, **kw), 2, 2)
    for (fi, fl), (gi, gl) in zip(full[2:], ff):
        np.testing.assert_array_equal(fi, gi)
        np.testing.assert_array_equal(fl, gl)


def test_cached_index_batches_fastforward():
    from mpi_pytorch_tpu.train.trainer import cached_index_batches

    cfg = Config(seed=5)
    full = list(cached_index_batches(cfg, 40, 8, epoch=2, n_steps=5))
    ff = list(cached_index_batches(cfg, 40, 8, epoch=2, n_steps=5, start_step=3))
    assert len(ff) == 2
    for (fi, fv), (gi, gv) in zip(full[3:], ff):
        np.testing.assert_array_equal(fi, gi)
        np.testing.assert_array_equal(fv, gv)


# ---------------------------------------------------------------------------
# the data cursor itself
# ---------------------------------------------------------------------------


def test_cursor_roundtrip_and_validation():
    from mpi_pytorch_tpu.train.trainer import data_cursor, validate_cursor

    cfg = Config()
    m = _synthetic_manifest(20)
    fp = manifest_fingerprint(m)
    cur = data_cursor(cfg, fp, 10, next_epoch=3, step_in_epoch=4)
    step, why = validate_cursor(
        cur, cfg=cfg, fingerprint=fp, n_steps=10, start_epoch=3
    )
    assert (step, why) == (4, None)
    # Every invalidation falls back with a reason, never misaligns.
    bad_fp, _ = validate_cursor(
        cur, cfg=cfg, fingerprint="deadbeef", n_steps=10, start_epoch=3
    )[0], None
    assert bad_fp == 0
    assert validate_cursor(
        cur, cfg=cfg, fingerprint=fp, n_steps=10, start_epoch=2
    ) == (0, "cursor epoch=3 != current 2")
    cfg2 = Config(batch_size=64)
    step2, why2 = validate_cursor(
        cur, cfg=cfg2, fingerprint=fp, n_steps=10, start_epoch=3
    )
    assert step2 == 0 and "global_batch" in why2
    assert validate_cursor(None, cfg=cfg, fingerprint=fp, n_steps=10,
                           start_epoch=3)[0] == 0


def test_manifest_fingerprint_is_order_sensitive():
    m = _synthetic_manifest(10)
    same = manifest_fingerprint(_synthetic_manifest(10))
    assert manifest_fingerprint(m) == same
    reordered = m.select(np.arange(9, -1, -1))
    assert manifest_fingerprint(reordered) != same


# ---------------------------------------------------------------------------
# trainer integration: exact-step resume (THE tentpole pin)
# ---------------------------------------------------------------------------


def _train_cfg(tmp_path, **kw) -> Config:
    c = Config()
    c.debug = True
    c.debug_sample_size = 64  # 51 train rows -> 3 steps/epoch at batch 16
    c.train_csv = os.path.join(os.path.dirname(__file__), "..", "data", "train_sample.csv")
    c.test_csv = os.path.join(os.path.dirname(__file__), "..", "data", "test_sample.csv")
    c.synthetic_data = True
    c.model_name = "resnet18"
    c.num_classes = 200
    c.batch_size = 16
    c.width = c.height = 16
    c.num_epochs = 3
    c.compute_dtype = "float32"
    c.checkpoint_dir = os.path.join(str(tmp_path), "ckpt")
    c.log_file = os.path.join(str(tmp_path), "training.log")
    c.metrics_file = os.path.join(str(tmp_path), "metrics.jsonl")
    c.validate = False
    c.loader_workers = 2
    c.log_every_steps = 0
    c.step_metrics = True
    c.resume_backoff_s = 0.0
    for k, v in kw.items():
        setattr(c, k, v)
    c.validate_config()
    return c


def _records(cfg):
    return [json.loads(line) for line in open(cfg.metrics_file) if line.strip()]


def _final_params(ckpt_dir):
    from mpi_pytorch_tpu.train.trainer import build_training

    cfg = Config()  # only used as a template container below
    path = ckpt.latest_checkpoint(ckpt_dir)
    assert path is not None
    from flax import serialization

    with open(path, "rb") as f:
        raw = serialization.msgpack_restore(f.read())
    return path, raw["params"]


def _flat(tree):
    return jax.tree_util.tree_leaves(tree)


def test_exact_step_resume_matches_uninterrupted(tmp_path, clean_gates):
    """Preempt mid-epoch (after step 4 = epoch 1 step 0) → dirty save with
    cursor (1, 1) → resume runs epoch 1 steps 1..2 and epoch 2 — final
    params equal the uninterrupted run's, and NO (epoch, step) pair is
    replayed across the two sessions."""
    from mpi_pytorch_tpu.train.trainer import train

    # Uninterrupted reference.
    ref_cfg = _train_cfg(tmp_path / "ref")
    train(ref_cfg)
    _, ref_params = _final_params(ref_cfg.checkpoint_dir)

    # Interrupted: stop right after the 4th completed step (epoch 1 step 0).
    cfg = _train_cfg(tmp_path / "run")
    os.environ["MPT_FAULT_PREEMPT_AT_STEP"] = "4"
    summary = train(cfg)
    assert summary.preempted
    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    assert os.path.exists(latest + ".dirty")
    manifest = ckpt.read_manifest(latest)
    assert manifest["data_cursor"]["epoch"] == 1
    assert manifest["data_cursor"]["step_in_epoch"] == 1

    os.environ.pop("MPT_FAULT_PREEMPT_AT_STEP")
    done = train(_train_cfg(tmp_path / "run", from_checkpoint=True))
    assert not done.preempted

    log = open(cfg.log_file).read()
    assert "exact-step resume: continuing epoch 1 at step 1" in log

    _, got_params = _final_params(cfg.checkpoint_dir)
    for a, b in zip(_flat(ref_params), _flat(got_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Zero replayed steps: across both sessions every (epoch, step) pair
    # appears exactly once, and the resume record carries the cursor.
    records = _records(cfg)
    pairs = [(r["epoch"], r["step"]) for r in records if r["kind"] == "step"]
    assert len(pairs) == len(set(pairs)) == 9, sorted(pairs)
    resume = [r for r in records if r["kind"] == "resume"][-1]
    assert resume["cursor_epoch"] == 1 and resume["cursor_step"] == 1
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(cfg.metrics_file) == []


def test_cursor_mismatch_falls_back_to_replay(tmp_path, clean_gates):
    """A tampered fingerprint invalidates the cursor: resume warns (typed
    kind='anomaly' reason='cursor_mismatch'), replays the interrupted epoch
    from step 0, and still completes."""
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(tmp_path)
    os.environ["MPT_FAULT_PREEMPT_AT_STEP"] = "4"
    assert train(cfg).preempted
    os.environ.pop("MPT_FAULT_PREEMPT_AT_STEP")

    latest = ckpt.latest_checkpoint(cfg.checkpoint_dir)
    manifest = ckpt.read_manifest(latest)
    manifest["data_cursor"]["manifest_fingerprint"] = "0" * 16
    ckpt.write_manifest(latest, manifest)

    done = train(_train_cfg(tmp_path, from_checkpoint=True))
    assert not done.preempted
    log = open(cfg.log_file).read()
    assert "exact-step resume unavailable" in log
    mismatches = [
        r for r in _records(cfg)
        if r["kind"] == "anomaly" and r["reason"] == "cursor_mismatch"
    ]
    assert mismatches and "manifest_fingerprint" in mismatches[0]["detail"]
    # The interrupted epoch was REPLAYED: epoch 1 step 0 appears twice.
    pairs = [(r["epoch"], r["step"]) for r in _records(cfg) if r["kind"] == "step"]
    assert pairs.count((1, 0)) == 2


# ---------------------------------------------------------------------------
# bad-step policy: skip
# ---------------------------------------------------------------------------


def _spmd_state_and_step(bad_step_skip):
    import flax.linen as nn
    import optax
    from jax.sharding import Mesh

    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_spmd_train_step, place_state_on_mesh

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape(x.shape[0], -1)
            return nn.Dense(8, name="head")(nn.relu(nn.Dense(13)(x)))

    model = MLP()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3)), train=True)
    state = TrainState.create(
        apply_fn=model.apply, variables=variables,
        tx=make_optimizer(1e-2), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8, 1), ("data", "model"))
    state = place_state_on_mesh(state, mesh)
    step = make_spmd_train_step(mesh, jnp.float32, bad_step_skip=bad_step_skip)
    return state, step, mesh


def test_skip_guard_keeps_params_bit_identical():
    from mpi_pytorch_tpu.parallel.mesh import shard_batch

    state, step, mesh = _spmd_state_and_step(bad_step_skip=True)
    rng = np.random.default_rng(0)
    clean = (rng.normal(size=(16, 8, 8, 3)).astype(np.float32),
             (np.arange(16) % 8).astype(np.int32))
    poisoned = (np.full((16, 8, 8, 3), np.nan, np.float32), clean[1])

    before = [np.asarray(x) for x in _flat(jax.device_get(state.params))]
    before_opt = [np.asarray(x) for x in _flat(jax.device_get(state.opt_state))]
    state, m = step(state, shard_batch(poisoned, mesh))
    assert int(m["skipped"]) == 1
    assert not np.isfinite(float(m["loss"]))
    after = [np.asarray(x) for x in _flat(jax.device_get(state.params))]
    after_opt = [np.asarray(x) for x in _flat(jax.device_get(state.opt_state))]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)  # bit-identical
    for a, b in zip(before_opt, after_opt):
        np.testing.assert_array_equal(a, b)
    assert int(jax.device_get(state.step)) == 0  # the update never happened

    # Training continues: the next clean step commits normally.
    state, m = step(state, shard_batch(clean, mesh))
    assert int(m["skipped"]) == 0
    assert np.isfinite(float(m["loss"]))
    assert int(jax.device_get(state.step)) == 1
    changed = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(before, _flat(jax.device_get(state.params)))
    )
    assert changed


def test_skip_guard_inside_scanned_epoch():
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import flax.linen as nn

    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_scanned_epoch, place_state_on_mesh

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(8)(x.reshape(x.shape[0], -1))

    model = MLP()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 4, 3)), train=True)
    state = TrainState.create(
        apply_fn=model.apply, variables=variables,
        tx=make_optimizer(1e-2), rng=jax.random.PRNGKey(1),
    )
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("data", "model"))
    state = place_state_on_mesh(state, mesh)
    rep = NamedSharding(mesh, P())
    rng = np.random.default_rng(0)
    dataset = rng.normal(size=(24, 4, 4, 3)).astype(np.float32)
    dataset[8:16] = np.nan  # the middle scan step gathers only NaN rows
    dataset = jax.device_put(dataset, rep)
    labels = jax.device_put((np.arange(24) % 8).astype(np.int32), rep)
    idx_all = np.arange(24, dtype=np.int32).reshape(3, 8)
    valid_all = np.ones((3, 8), bool)
    epoch_fn = make_scanned_epoch(mesh, jnp.float32, bad_step_skip=True)
    state, m = epoch_fn(state, dataset, labels, idx_all, valid_all)
    np.testing.assert_array_equal(np.asarray(m["skipped"]), [0, 1, 0])
    # The scan carried the pre-step state through the bad step: params stay
    # finite and two updates committed.
    assert int(jax.device_get(state.step)) == 2
    for leaf in _flat(jax.device_get(state.params)):
        assert np.isfinite(np.asarray(leaf)).all()


def test_skip_policy_trainer_survives_injected_nonfinite(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(tmp_path, bad_step_policy="skip", num_epochs=2)
    os.environ["MPT_FAULT_NONFINITE_AT_STEP"] = "2"
    summary = train(cfg)
    assert summary.epochs_run == 2
    records = _records(cfg)
    skipped = [r for r in records if r["kind"] == "step" and r.get("skipped")]
    assert len(skipped) == 1 and skipped[0]["steps_skipped"] == 1
    assert (skipped[0]["epoch"], skipped[0]["step"]) == (0, 1)
    faults = [r for r in records if r["kind"] == "fault"]
    assert any(f["reason"] == "injected_nonfinite" for f in faults)
    # The injection is announced BEFORE the poisoned step's record.
    fault_ts = [f["ts"] for f in faults if f["reason"] == "injected_nonfinite"][0]
    assert fault_ts <= skipped[0]["ts"]
    # Epoch accounting masked the skipped step: the epoch loss is finite.
    epoch0 = [r for r in records if r["kind"] == "epoch" and r["epoch"] == 0][0]
    assert np.isfinite(epoch0["loss"])
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(cfg.metrics_file) == []


def test_skip_policy_aborts_at_limit(tmp_path, clean_gates):
    from mpi_pytorch_tpu.obs.health import NonFiniteLossError
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(
        tmp_path, bad_step_policy="skip", max_skipped_steps=1, num_epochs=2
    )
    os.environ["MPT_FAULT_NONFINITE_AT_STEP"] = "2"
    with pytest.raises(NonFiniteLossError, match="max-skipped-steps"):
        train(cfg)
    assert any(
        r["kind"] == "anomaly" and r["reason"] == "skip_limit"
        for r in _records(cfg)
    )


# ---------------------------------------------------------------------------
# bad-step policy: rollback
# ---------------------------------------------------------------------------


def test_rollback_policy_observe_streak_and_drift():
    p = elastic.RollbackPolicy(nonfinite_steps=2, loss_drift=3.0, drift_warmup=2)
    assert p.observe(1.0, 1.0) is None  # warmup 1
    assert p.observe(1.0, 1.0) is None  # warmup 2 (baseline = 1.0)
    assert p.observe(float("nan"), 1.0) is None  # streak 1 of 2
    assert p.observe(2.0, float("inf")) == "nonfinite_streak"  # streak 2
    p.after_rollback()
    assert p.nonfinite_streak == 0
    assert p.observe(2.9, 1.0) is None  # 2.9x baseline: under 3.0
    assert p.observe(3.5, 1.0) == "loss_drift"


def test_rollback_trainer_restores_in_process(tmp_path, clean_gates):
    """NaN injected mid-epoch 1 under rollback policy: ONE kind='rollback'
    record, the run restores epoch 0's checkpoint WITHOUT exiting, re-runs
    epoch 1 cleanly, and completes all epochs — spmd+ZeRO, so the restore
    exercises the unsharded-template path."""
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(
        tmp_path, bad_step_policy="rollback", rollback_nonfinite_steps=1,
        num_epochs=3, spmd_mode=True, zero_opt_state=True,
    )
    os.environ["MPT_FAULT_NONFINITE_AT_STEP"] = "5"  # epoch 1 step 1
    summary = train(cfg)
    assert summary.epochs_run >= 3  # epoch 1 ran twice; all epochs completed
    records = _records(cfg)
    rollbacks = [r for r in records if r["kind"] == "rollback"]
    assert len(rollbacks) == 1, rollbacks
    rb = rollbacks[0]
    assert rb["reason"] == "nonfinite_streak"
    assert (rb["epoch"], rb["step"]) == (1, 1)
    assert rb["restored_epoch"] == 0 and rb["rollbacks"] == 1
    # The in-process restore wrote a resume record; the run never exited.
    assert any(r["kind"] == "resume" for r in records)
    epochs = {r["epoch"] for r in records if r["kind"] == "epoch"}
    assert epochs == {0, 1, 2}
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(cfg.metrics_file) == []


def test_rollback_without_checkpoint_aborts(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(
        tmp_path, bad_step_policy="rollback", rollback_nonfinite_steps=1,
    )
    os.environ["MPT_FAULT_NONFINITE_AT_STEP"] = "1"  # before any checkpoint
    with pytest.raises(elastic.RollbackLimitError, match="no checkpoint"):
        train(cfg)


def test_rollback_lr_backoff_scales_and_records(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(
        tmp_path, bad_step_policy="rollback", rollback_nonfinite_steps=1,
        rollback_lr_backoff=0.5, num_epochs=3,
    )
    os.environ["MPT_FAULT_NONFINITE_AT_STEP"] = "5"
    summary = train(cfg)
    assert summary.epochs_run >= 3
    rb = [r for r in _records(cfg) if r["kind"] == "rollback"][0]
    assert rb["lr_scale"] == 0.5


# ---------------------------------------------------------------------------
# decode-failure quarantine (data/pipeline.py satellite)
# ---------------------------------------------------------------------------


def test_decode_failure_retries_then_quarantines(tmp_path, clean_gates):
    m = _synthetic_manifest(12)
    dl = DataLoader(
        m, batch_size=4, image_size=(8, 8), shuffle=False, synthetic=True,
        num_workers=2, decode_retries=2, decode_retry_backoff_s=0.0,
        quarantine_file=str(tmp_path / "quarantine.txt"),
    )
    dl.metrics = FakeMetrics()
    # One poisoned sample: every attempt (1 original + 2 retries) fails,
    # so exactly ONE sample exhausts its retries and is quarantined.
    os.environ["MPT_FAULT_DECODE_N"] = "1"
    reset_fault_counters()
    batches = list(dl.epoch(0))
    assert dl.bad_samples == 1
    labels = np.concatenate([l for _, l in batches])
    assert (labels == -1).sum() == 1
    anomalies = [r for r in dl.metrics.records if r["kind"] == "anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["reason"] == "bad_sample"
    assert "injected decode failure" in anomalies[0]["detail"]
    quarantine = open(tmp_path / "quarantine.txt").read()
    assert anomalies[0]["path"] in quarantine
    # Later epochs keep the row masked (one -1 label per epoch).
    labels1 = np.concatenate([l for _, l in dl.epoch(1)])
    assert (labels1 == -1).sum() == 1


def test_decode_failure_budget_aborts_loudly(tmp_path, clean_gates):
    m = _synthetic_manifest(12)
    dl = DataLoader(
        m, batch_size=4, image_size=(8, 8), shuffle=False, synthetic=True,
        num_workers=1, decode_retries=0, decode_retry_backoff_s=0.0,
        max_bad_samples=1,
    )
    os.environ["MPT_FAULT_DECODE_N"] = "2"  # two poisoned samples -> budget blown
    reset_fault_counters()
    with pytest.raises(BadSampleLimitError, match="max_bad_samples"):
        for _ in dl.epoch(0):
            pass


def test_trainer_quarantine_writes_anomaly_records(tmp_path, clean_gates):
    from mpi_pytorch_tpu.train.trainer import train

    cfg = _train_cfg(tmp_path, num_epochs=1,
                     quarantine_file=str(tmp_path / "q.txt"))
    os.environ["MPT_FAULT_DECODE_N"] = "1"  # one poisoned sample -> 1 quarantine
    summary = train(cfg)
    assert summary.epochs_run == 1
    bad = [
        r for r in _records(cfg)
        if r["kind"] == "anomaly" and r["reason"] == "bad_sample"
    ]
    assert len(bad) == 1 and bad[0]["path"]
    assert os.path.exists(tmp_path / "q.txt")
    from mpi_pytorch_tpu.obs.schema import validate_jsonl

    assert validate_jsonl(cfg.metrics_file) == []


# ---------------------------------------------------------------------------
# gates, config, rendering
# ---------------------------------------------------------------------------


def test_new_gates_registered_and_in_fault_env():
    from tools.inject_faults import fault_env

    for gate in (
        "MPT_FAULT_NONFINITE_AT_STEP",
        "MPT_FAULT_DECODE_N",
        "MPT_FAULT_PREEMPT_AT_STEP",
    ):
        assert gate in FAULT_GATES
    env = fault_env(nonfinite_at_step=3, decode_fail=2, preempt_at_step=7)
    assert env["MPT_FAULT_NONFINITE_AT_STEP"] == "3"
    assert env["MPT_FAULT_DECODE_N"] == "2"
    assert env["MPT_FAULT_PREEMPT_AT_STEP"] == "7"


def test_config_validates_selfheal_knobs():
    with pytest.raises(ValueError, match="bad_step_policy"):
        Config(bad_step_policy="retry").validate_config()
    with pytest.raises(ValueError, match="max_skipped_steps"):
        Config(max_skipped_steps=0).validate_config()
    with pytest.raises(ValueError, match="rollback_loss_drift"):
        Config(rollback_loss_drift=0.5).validate_config()
    with pytest.raises(ValueError, match="rollback_lr_backoff"):
        Config(rollback_lr_backoff=0.0).validate_config()
    with pytest.raises(ValueError, match="scan_epoch"):
        Config(
            bad_step_policy="rollback", device_cache=True, scan_epoch=True
        ).validate_config()
    with pytest.raises(ValueError, match="max_bad_samples"):
        Config(max_bad_samples=-1).validate_config()
    Config(
        bad_step_policy="rollback", rollback_loss_drift=2.0,
        rollback_lr_backoff=0.5,
    ).validate_config()
    Config(bad_step_policy="skip", max_skipped_steps=3).validate_config()


def test_schema_v6_records_validate():
    from mpi_pytorch_tpu.obs.schema import validate_record

    assert validate_record({
        "ts": 1.0, "kind": "rollback", "epoch": 2, "reason": "nonfinite_streak",
        "step": 3, "restored_epoch": 1, "rollbacks": 1, "lr_scale": 0.5,
        "path": "ckpt/ckpt_00001.msgpack",
    }) == []
    assert validate_record({
        "ts": 1.0, "kind": "step", "epoch": 0, "step": 1, "loss": float("nan"),
        "skipped": 1, "steps_skipped": 4,
    }) == []
    assert validate_record({
        "ts": 1.0, "kind": "resume", "epoch": 1, "to_devices": 8,
        "cursor_epoch": 2, "cursor_step": 3,
    }) == []
    assert validate_record({
        "ts": 1.0, "kind": "anomaly", "reason": "bad_sample", "epoch": 0,
        "path": "img/x.jpg", "detail": "truncated",
    }) == []
    assert validate_record({"ts": 1.0, "kind": "rollback", "epoch": 1}) != []


def test_report_run_renders_rollback_and_skips(tmp_path, capsys):
    from tools import report_run

    path = tmp_path / "m.jsonl"
    records = [
        {"ts": 1.0, "kind": "step", "epoch": 0, "step": 0, "loss": 1.0,
         "skipped": 0, "steps_skipped": 0},
        {"ts": 2.0, "kind": "step", "epoch": 0, "step": 1,
         "loss": float("nan"), "skipped": 1, "steps_skipped": 1},
        {"ts": 3.0, "kind": "rollback", "epoch": 2, "step": 1,
         "reason": "loss_drift", "restored_epoch": 1, "rollbacks": 1,
         "lr_scale": 0.5, "path": "ckpt/ckpt_00001.msgpack"},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in records))
    assert report_run.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "skipped steps (bad-step policy): 1 discarded, longest streak 1" in out
    assert "ROLLBACK: #1 — loss_drift at epoch 2 step 1 → restored epoch 1" in out
    assert "LR scaled to 0.5x" in out


# ---------------------------------------------------------------------------
# cross-mesh exact-step continuity (8 -> 4 devices; subprocess, slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_cross_mesh_exact_step_resume(tmp_path):
    """Mid-epoch preempt on an 8-device mesh, resume on 4: the cursor lives
    in global-sample space, so the fast-forward continues at the same
    global step with the same batches — no replayed (epoch, step) pairs."""
    import subprocess
    import sys

    from tools.inject_faults import fault_env

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    args = [
        sys.executable, "-m", "mpi_pytorch_tpu.train",
        "--debug", "true", "--debug-sample-size", "64", "--num-classes", "200",
        "--batch-size", "16", "--width", "16", "--height", "16",
        "--synthetic-data", "true", "--validate", "false",
        "--compute-dtype", "float32", "--loader-workers", "2",
        "--log-every-steps", "0", "--step-metrics", "true",
        "--num-epochs", "3", "--checkpoint-every-epochs", "1",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        "--log-file", str(tmp_path / "training.log"),
        "--metrics-file", str(tmp_path / "metrics.jsonl"),
    ]

    def env_for(n, **faults):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = env["MPT_PLATFORM"] = "cpu"
        flags = [
            f for f in env.get("XLA_FLAGS", "").split()
            if "xla_force_host_platform_device_count" not in f
        ]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n}"]
        )
        return fault_env(base=env, **faults)

    subprocess.run(
        args, env=env_for(8, preempt_at_step=4), cwd=REPO, check=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    subprocess.run(
        args + ["--from-checkpoint", "true"], env=env_for(4), cwd=REPO,
        check=True, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    records = [
        json.loads(line) for line in open(tmp_path / "metrics.jsonl")
        if line.strip()
    ]
    pairs = [(r["epoch"], r["step"]) for r in records if r["kind"] == "step"]
    assert len(pairs) == len(set(pairs)) == 9, sorted(pairs)
    resume = [r for r in records if r["kind"] == "resume"][-1]
    assert resume["from_devices"] == 8 and resume["to_devices"] == 4
    assert resume["cursor_epoch"] == 1 and resume["cursor_step"] == 1
    assert {r["epoch"] for r in records if r["kind"] == "epoch"} == {0, 1, 2}
