"""Multi-model tenancy (ISSUE 14): serve the whole zoo as tenants.

- ``registry.py`` — tenant specs (``--serve-models``), the
  ``ModelRegistry``, and the VMEM/HBM-aware packing planner whose
  explainable plan is stamped on swap-in records.
- ``pool.py`` — per-(model, bucket[, precision]) AOT executable sets,
  built lazily and shared across hosts, with the cold swap-in
  load → warm-probe → activate gate.
- ``server.py`` — ``ZooServer`` (one host, many tenants: per-tenant
  pipelines over one mesh, single-tenant flushes by construction, LRU
  eviction under the packing budget, ``facts_generation`` coherence)
  plus the router/controller handles (``ZooHost``, ``TenantHandle``).
"""

from mpi_pytorch_tpu.serve.zoo.pool import ColdSwapError, ZooExecutablePool
from mpi_pytorch_tpu.serve.zoo.registry import (
    ModelRegistry,
    ModelSpec,
    PackingError,
    PackingPlan,
    UnknownModelError,
    parse_model_specs,
)
from mpi_pytorch_tpu.serve.zoo.server import (
    ModelNotResidentError,
    TenantHandle,
    ZooHost,
    ZooServer,
)

__all__ = [
    "ColdSwapError",
    "ModelNotResidentError",
    "ModelRegistry",
    "ModelSpec",
    "PackingError",
    "PackingPlan",
    "TenantHandle",
    "UnknownModelError",
    "ZooExecutablePool",
    "ZooHost",
    "ZooServer",
    "parse_model_specs",
]
