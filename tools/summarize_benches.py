"""Render the chip battery's JSON artifacts as RESULTS-ready markdown.

``tools/run_chip_benches.sh`` leaves docs/{bench_latest,zoo_bench,
zoo_flash,modes_bench,attention_bench,eval_bench}.json plus the flag-sweep
and roofline text files. This prints the markdown tables those artifacts
support, so folding a battery into docs/RESULTS.md is one command whenever
the relay comes back (possibly in a later session):

    python tools/summarize_benches.py [docs]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_pytorch_tpu.obs.replay import render_diff  # noqa: E402


def _load(path):
    if not os.path.exists(path):
        return None
    with open(path) as f:
        try:
            return json.load(f)
        except json.JSONDecodeError:
            # corrupt != absent: a relay wedge can truncate an artifact
            # mid-write, and that stage must not silently vanish.
            print(f"WARNING: {path} exists but is not valid JSON "
                  "(truncated battery stage?)", file=sys.stderr)
            return None


def _load_jsonl(path):
    """One JSON object per line (tools/bench_eval.py output)."""
    if not os.path.exists(path):
        return None
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"WARNING: bad JSONL line in {path}",
                          file=sys.stderr)
    return rows or None


def _cell(text) -> str:
    """Escape markdown-table separators in interpolated text (bench_zoo
    error strings contain literal | separators)."""
    return str(text).replace("|", "\\|")


def main() -> None:
    out = sys.argv[1] if len(sys.argv) > 1 else "docs"

    headline = _load(os.path.join(out, "bench_latest.json"))
    if headline:
        print("## headline\n")
        print("```json")
        print(json.dumps(headline))
        print("```\n")

    zoo = _load(os.path.join(out, "zoo_bench.json"))
    if zoo:
        print("## zoo (§3b)\n")
        print("| model | batch/chip | img/s/chip | step ms | TFLOP/s | MFU |")
        print("|---|---|---|---|---|---|")
        for r in zoo:
            if "error" in r:
                print(f"| {r['model']} | — | ERROR: {_cell(r['error'][:60])} | | | |")
                continue
            print(
                f"| {r['model']} | {r['batch_per_chip']} | "
                f"{r['images_per_sec_per_chip']:,.0f} | {r['step_ms']} | "
                f"{r['tflops_per_chip']} | {r.get('mfu_pct', '?')}% |"
            )
        print()

    flash = _load(os.path.join(out, "zoo_flash.json"))
    if flash:
        print("## vit flash vs full (zoo rows above are full)\n")
        for r in flash:
            print(json.dumps(r))
        print()

    s2d = _load(os.path.join(out, "zoo_s2d.json"))
    if s2d:
        print("## resnet space-to-depth stem vs standard (zoo rows above "
              "are the standard stem)\n")
        for r in s2d:
            print(json.dumps(r))
        print()

    vmem = _load(os.path.join(out, "flags_vmem_sweep.json"))
    if vmem:
        print("## scoped-VMEM compiler-option sweep (headline)\n")
        print("| set | img/s/chip | MFU |")
        print("|---|---|---|")
        for r in vmem:
            print(f"| {_cell(r.get('label'))} | {r.get('value', 0):,.0f} | "
                  f"{r.get('mfu_pct', '?')}% |")
        print()

    modes = _load(os.path.join(out, "modes_bench.json"))
    if modes:
        print("## input/execution modes (§4c)\n")
        print("| mode | img/s/chip | vs baseline |")
        print("|---|---|---|")
        for r in modes:
            if "error" in r:
                print(f"| {r['mode']} | ERROR: {_cell(r['error'][:60])} | |")
                continue
            print(
                f"| {r['mode']} | {r['images_per_sec_per_chip']:,.0f} | "
                f"{r['vs_baseline']:,.0f}× |"
            )
        print()

    attn = _load(os.path.join(out, "attention_bench.json"))
    if attn:
        print("## attention microbench (flash vs full)\n")
        print("| S | full ms | flash ms | speedup | full temp MB | flash temp MB |")
        print("|---|---|---|---|---|---|")
        by_seq: dict[int, dict] = {}
        for r in attn:
            by_seq.setdefault(r["seq"], {})[r["impl"]] = r
        for seq in sorted(by_seq):
            f_, fl = by_seq[seq].get("full", {}), by_seq[seq].get("flash", {})
            if "error" in f_ or "error" in fl or not f_ or not fl:
                # keep whichever side succeeded, name the one that failed
                def fmt(r, impl):
                    if not r:
                        return f"{impl}: missing"
                    if "error" in r:
                        return f"{impl}: {_cell(r['error'][:50])}"
                    return f"{r['fwd_bwd_ms']} ms"
                print(f"| {seq} | {fmt(f_, 'full')} | {fmt(fl, 'flash')} | | | |")
                continue
            sp = f_["fwd_bwd_ms"] / fl["fwd_bwd_ms"] if fl["fwd_bwd_ms"] else 0
            print(
                f"| {seq} | {f_['fwd_bwd_ms']} | {fl['fwd_bwd_ms']} | "
                f"{sp:.2f}× | {f_.get('temp_hbm_mb', '?')} | "
                f"{fl.get('temp_hbm_mb', '?')} |"
            )
        print()

    ev = _load_jsonl(os.path.join(out, "eval_bench.json"))
    if ev:
        print("## inference bench\n")
        for r in ev:
            print(json.dumps(r))
        print()

    sb = _load_jsonl(os.path.join(out, "serve_bench.json"))
    if sb:
        print("## serving latency vs load (tools/bench_serve.py)\n")
        # The v10 tenant columns: only rendered when some row carries a
        # load_shape (a multi-tenant sweep) — single-model artifacts
        # print the same table as before.
        tenants = any(r.get("load_shape") for r in sb)
        tenant_head = "model | shape | " if tenants else ""
        # The v14 workload column: only rendered when some row replayed a
        # fingerprinted workload — pre-v14 artifacts print the same table.
        replays = any(r.get("workload") for r in sb)
        workload_head = "workload | " if replays else ""
        print(f"| mode | buckets | wait ms | offered rps | {tenant_head}"
              f"{workload_head}"
              "prec | fleet | p50 ms | p95 ms | p99 ms | img/s | fill | "
              "rejected | compiles |")
        print("|---" * (13 + (2 if tenants else 0) + (1 if replays else 0))
              + "|")
        for r in sb:
            rps = r.get("offered_rps")
            tenant_cells = (
                f"{r.get('model') or '—'} | {r.get('load_shape') or '—'} | "
                if tenants else ""
            )
            workload_cells = (
                f"{r.get('workload') or '—'} | " if replays else ""
            )
            print(
                f"| {r['mode']} | {_cell(r['buckets'])} | {r['max_wait_ms']} | "
                f"{'—' if rps is None else rps} | "
                f"{tenant_cells}"
                f"{workload_cells}"
                f"{r.get('precision') or 'bf16'} | "
                f"{r.get('fleet_hosts') or '—'} | {r['p50_ms']} | "
                f"{r['p95_ms']} | {r['p99_ms']} | {r['images_per_sec']:,.0f} | "
                f"{r.get('mean_fill_ratio', '?')} | {r.get('rejected', '?')} | "
                f"{r.get('compiles_after_warmup', '?')} |"
            )
        parities = {
            r["parity_top1"] for r in sb if r.get("parity_top1") is not None
        }
        if parities:
            print(
                "\nint8 rows: startup top-1 parity vs bf16 = "
                + ", ".join(str(p) for p in sorted(parities))
                + " (ops/quantize.py; offline oracle: evaluate --quantize-eval)"
            )
        # The v9 per-phase columns (collector-derived attribution): only
        # rendered when some row carries per_phase, so pre-v9 artifacts
        # print the same tables as before.
        pp_rows = [r for r in sb if r.get("per_phase")]
        if pp_rows:
            print("\n### per-phase p99 attribution (tools/trace_report.py "
                  "renders the waterfalls)\n")
            phases = sorted({
                p for r in pp_rows for p in r["per_phase"]
            })
            print("| mode | buckets | wait ms | " +
                  " | ".join(f"{p} p99" for p in phases) + " |")
            print("|---" * (3 + len(phases)) + "|")
            for r in pp_rows:
                cells = [
                    str((r["per_phase"].get(p) or {}).get("p99_ms", "—"))
                    for p in phases
                ]
                print(f"| {r['mode']} | {_cell(r['buckets'])} | "
                      f"{r['max_wait_ms']} | " + " | ".join(cells) + " |")
        # The v14 replay differential: recorded vs replayed per-phase p99
        # for rows that re-drove a fingerprinted workload (cite the
        # fingerprint when quoting these numbers — SERVING.md).
        diff_rows = [r for r in sb if isinstance(r.get("replay_diff"), dict)]
        if diff_rows:
            print("\n### trace-replay differential "
                  "(tools/bench_serve.py --replay)\n")
            print("```")
            for r in diff_rows:
                for ln in render_diff(r["replay_diff"]):
                    print(ln)
            print("```")
        print()

    for name in ("roofline_resnet18.txt", "roofline_densenet121.txt",
                 "flags_sweep.txt", "flags_densenet.txt",
                 "flags_squeezenet.txt"):
        p = os.path.join(out, name)
        if os.path.exists(p):
            print(f"## {name}\n")
            with open(p) as f:
                print(f.read().strip()[:4000])
            print()


if __name__ == "__main__":
    main()
