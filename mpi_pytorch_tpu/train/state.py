"""Train state: params + BN running stats + optimizer state + step + rng.

The reference's analogue is the (model, optimizer) pair of torch objects
(``main.py:121-125``) whose state lives implicitly in mutable modules. Here
it is one immutable pytree, which is what makes the whole step jittable and
shardable.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any  # None for BN-free models (alexnet, squeezenet)
    opt_state: Any
    rng: jax.Array
    # static (non-pytree) fields:
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)

    @classmethod
    def create(cls, *, apply_fn, variables: dict, tx, rng: jax.Array) -> "TrainState":
        params = variables["params"]
        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            batch_stats=variables.get("batch_stats"),
            opt_state=tx.init(params),
            rng=rng,
            apply_fn=apply_fn,
            tx=tx,
        )

    @property
    def variables(self) -> dict:
        v = {"params": self.params}
        if self.batch_stats is not None:
            v["batch_stats"] = self.batch_stats
        return v


def make_optimizer(
    learning_rate: float,
    trainable_mask: Any | None = None,
    *,
    optimizer: str = "adam",
    lr_schedule: str = "constant",
    warmup_steps: int = 0,
    total_steps: int | None = None,
    weight_decay: float = 0.0,
) -> optax.GradientTransformation:
    """Optimizer factory. Defaults reproduce the reference exactly:
    Adam(lr) with a constant rate (≙ ``main.py:125``). Beyond parity:

    - ``optimizer``: ``adam`` | ``sgd`` (momentum 0.9) | ``adamw``
      (decoupled ``weight_decay``);
    - ``lr_schedule``: ``constant`` | ``cosine`` (decay to 0 over
      ``total_steps``) | ``warmup_cosine`` (linear warmup over
      ``warmup_steps`` then cosine) — schedules are optax schedule
      functions, evaluated inside the jitted step from the optimizer
      state's own step counter;
    - ``feature_extract``: with ``trainable_mask``, non-head params get
      zero updates — the optax expression of ``requires_grad=False``
      (reference ``models.py:5-13``).
    """
    if lr_schedule == "constant":
        lr: Any = learning_rate
    elif lr_schedule in ("cosine", "warmup_cosine"):
        if not total_steps or total_steps <= 0:
            raise ValueError(f"lr_schedule={lr_schedule!r} requires total_steps > 0")
        warmup = warmup_steps if lr_schedule == "warmup_cosine" else 0
        if warmup < 0:
            raise ValueError(f"warmup_steps must be >= 0, got {warmup}")
        if warmup >= total_steps:
            raise ValueError(
                f"warmup_steps ({warmup}) must be < the run's total step "
                f"count ({total_steps}); shorten the warmup or train longer"
            )
        if warmup > 0:
            lr = optax.warmup_cosine_decay_schedule(
                init_value=0.0, peak_value=learning_rate,
                warmup_steps=warmup, decay_steps=total_steps,
            )
        else:
            lr = optax.cosine_decay_schedule(learning_rate, decay_steps=total_steps)
    else:
        raise ValueError(
            f"lr_schedule must be constant|cosine|warmup_cosine, got {lr_schedule!r}"
        )

    if optimizer == "adam":
        tx = optax.adam(lr)
    elif optimizer == "sgd":
        tx = optax.sgd(lr, momentum=0.9)
    elif optimizer == "adamw":
        tx = optax.adamw(lr, weight_decay=weight_decay)
    else:
        raise ValueError(f"optimizer must be adam|sgd|adamw, got {optimizer!r}")

    if trainable_mask is None:
        return tx
    labels = jax.tree_util.tree_map(lambda t: "train" if t else "freeze", trainable_mask)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )
