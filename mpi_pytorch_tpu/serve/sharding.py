"""Serve-side model-parallel residency: TP/FSDP param sharding over the
nested ``(data, model)`` serve mesh, plus bounded cross-topology resharding.

A tenant's RESIDENCY names how its weights sit on the serve mesh:

- ``replicated`` — every chip holds the full tree (the only option before
  ISSUE 17; still the right one for models that fit).
- ``tp:K`` — Megatron-style tensor parallelism over ``model``: the
  64.5k-class head kernel/bias column-shard over K chips (the trainer's
  ``param_specs`` head rule, reused verbatim on the serve mesh), trunk
  replicated. Cheap where it counts: the head is ~25% of resnet18's bytes.
- ``fsdp:K`` — every leaf shards its first K-divisible dimension over
  ``model`` (the ZeRO shard-selection rule, ``shard_first_divisible``).
  At rest each chip holds ~1/K of the weights; XLA all-gathers each
  layer just before use inside the compiled bucket executable.

Cross-topology moves (replicated↔tp↔fsdp, degree changes) go through
``reshard_state``: host-stage one leaf at a time, then place each target
device's shard directly from the host buffer via the PR 7 bounded
redistribution core (``train/state.redistribute_to``) — the peak device
transient is ONE shard and there is never a device-side gather of the
full tree (arXiv 2112.01075's discipline; arXiv 2004.13336 is the
cross-replica residency blueprint). The per-leaf byte/chunk accounting
rides back on ``ReshardStats`` and lands on swap-in records as
``reshard_bytes`` (schema v13).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from mpi_pytorch_tpu.parallel.mesh import (
    is_head_kernel,
    model_axis_name,
    shard_first_divisible,
)

RESIDENCY_KINDS = ("replicated", "tp", "fsdp", "pipe")


@dataclasses.dataclass(frozen=True)
class Residency:
    """One tenant's weight layout on the serve mesh."""

    kind: str = "replicated"
    degree: int = 1

    def __post_init__(self):
        if self.kind not in RESIDENCY_KINDS:
            raise ValueError(
                f"unknown residency kind {self.kind!r} "
                f"(expected one of {RESIDENCY_KINDS})"
            )
        if self.kind == "replicated" and self.degree != 1:
            raise ValueError("replicated residency has degree 1 by definition")
        if self.kind != "replicated" and self.degree < 2:
            raise ValueError(
                f"{self.kind} residency needs degree >= 2, got {self.degree}"
            )

    @property
    def sharded(self) -> bool:
        return self.kind != "replicated"

    def __str__(self) -> str:
        return self.kind if not self.sharded else f"{self.kind}:{self.degree}"


REPLICATED = Residency()


def parse_residency(text: str | None) -> Residency:
    """``"replicated"``/``""``/None → replicated; ``"tp:K"``/``"fsdp:K"``
    → sharded; bare ``"K"`` (the zoo spec's ``shard=K`` shorthand) → fsdp:K
    — FSDP is the default split because it divides EVERY leaf, so it is the
    one that makes a too-big tenant fit."""
    if not text or text == "replicated":
        return REPLICATED
    s = str(text).strip().lower()
    if s.isdigit():
        return Residency("fsdp", int(s))
    kind, sep, deg = s.partition(":")
    if not sep or kind not in ("tp", "fsdp", "pipe") or not deg.isdigit():
        raise ValueError(
            f"unparseable residency {text!r} (expected 'replicated', "
            "'tp:K', 'fsdp:K', 'pipe:K', or bare 'K' for fsdp:K)"
        )
    return Residency(kind, int(deg))


@dataclasses.dataclass
class ReshardStats:
    """Byte accounting for one residency move, chunk-bounded by
    construction: ``peak_chunk_bytes`` is the largest single device_put the
    move performed — the transient-HBM bound the tests assert."""

    residency: str = "replicated"
    leaves: int = 0
    sharded_leaves: int = 0
    bytes_moved: int = 0
    peak_chunk_bytes: int = 0

    def to_record(self) -> dict:
        return {
            "residency": self.residency,
            "leaves": self.leaves,
            "sharded_leaves": self.sharded_leaves,
            "bytes_moved": int(self.bytes_moved),
            "peak_chunk_bytes": int(self.peak_chunk_bytes),
        }


def serve_param_specs(tree: Any, mesh, residency: Residency) -> Any:
    """PartitionSpecs for a serve state tree under ``residency``. TP reuses
    the trainer's head rule (``is_head_kernel`` + last-dim split); FSDP
    shards every leaf's first K-divisible dim over the MODEL axis — the
    serve twist on the ZeRO rule: the trainer FSDPs over ``data`` because
    its data axis is the big one, but a serve tenant's K chips are the
    ``model`` axis, and the ``data`` axis must keep holding independent
    batch rows (and other tenants)."""
    if residency.kind == "pipe":
        # Pipeline residency is not a tree-wide spec rule: each leaf lives
        # ONLY on its stage's chip group, and the stage assignment is the
        # cut planner's job (serve/pipeline.py places leaves itself).
        raise ValueError(
            "pipe residency has no per-leaf PartitionSpec mapping; build "
            "serve.pipeline.PipelineExecutables instead"
        )
    model_axis = mesh.axis_names[-1] if len(mesh.axis_names) == 1 else model_axis_name(mesh)
    msize = int(mesh.shape[model_axis])
    if residency.sharded and residency.degree != msize:
        raise ValueError(
            f"residency {residency} does not match the mesh model axis "
            f"({model_axis}={msize}); build the serve mesh with "
            f"create_serve_mesh({residency.degree})"
        )

    def spec(path, leaf):
        shape = tuple(np.shape(leaf))
        if not residency.sharded or msize == 1 or not shape:
            return P()
        if residency.kind == "fsdp":
            return shard_first_divisible(shape, model_axis, msize)
        is_head, is_kernel = is_head_kernel(path)
        if not is_head:
            return P()
        if is_kernel and len(shape) >= 2 and shape[-1] % msize == 0:
            return P(*([None] * (len(shape) - 1) + [model_axis]))
        if len(shape) == 1 and shape[0] % msize == 0:
            return P(model_axis)
        return P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def serve_shardings(tree: Any, mesh, residency: Residency) -> Any:
    specs = serve_param_specs(tree, mesh, residency)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _shard_nbytes(shape, dtype, sharding) -> int:
    shard_shape = sharding.shard_shape(tuple(shape))
    n = 1
    for d in shard_shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def reshard_state(
    state: Any, mesh, residency: Residency, *, logger=None
) -> tuple[Any, ReshardStats]:
    """Move a (possibly already device-resident, possibly differently
    sharded, possibly on a different mesh) state tree to ``residency`` on
    ``mesh``. One leaf at a time: host-stage (``device_get`` assembles from
    the source's addressable shards on HOST — no device gather), then place
    each target shard directly (``redistribute_to``). Leaves already carrying
    the target sharding are left in place and cost zero bytes. Returns the
    resharded tree plus the chunk-bounded byte accounting."""
    from mpi_pytorch_tpu.train.state import redistribute_to
    from mpi_pytorch_tpu.utils.env import fault_countdown

    shardings = serve_shardings(state, mesh, residency)
    stats = ReshardStats(residency=str(residency))
    fail_mid_tree = fault_countdown("MPT_FAULT_RESHARD_N")

    def move(leaf, target):
        if not hasattr(leaf, "ndim"):
            return leaf
        stats.leaves += 1
        if fail_mid_tree and stats.leaves > 1:
            # After the first leaf has been placed: the half-moved state
            # the failure-path tests need (MPT_FAULT_RESHARD_N).
            raise RuntimeError(
                "injected fault: residency reshard died mid-tree "
                "(MPT_FAULT_RESHARD_N)"
            )
        if isinstance(leaf, jax.Array) and leaf.sharding == target:
            return leaf
        if not target.spec == P():
            stats.sharded_leaves += 1
        if leaf.ndim == 0:
            return jax.device_put(np.asarray(leaf), target)
        host = np.asarray(jax.device_get(leaf))
        chunk = _shard_nbytes(host.shape, host.dtype, target)
        n_puts = len(target.addressable_devices_indices_map(host.shape))
        stats.bytes_moved += chunk * n_puts
        stats.peak_chunk_bytes = max(stats.peak_chunk_bytes, chunk)
        return redistribute_to(host, target)

    moved = jax.tree_util.tree_map(move, state, shardings)
    if logger is not None:
        logger.info(
            "resharded state to %s: %d/%d leaves sharded, %.1f MB moved, "
            "peak chunk %.2f MB",
            residency, stats.sharded_leaves, stats.leaves,
            stats.bytes_moved / 1e6, stats.peak_chunk_bytes / 1e6,
        )
    return moved, stats
