"""Collective operations — the TPU-native ``mpi_tools.py``.

Exhaustive parity map to the reference's wrapper (``mpi_tools.py:5-53``):

| reference (MPI)                         | here (XLA collectives over ICI)     |
|-----------------------------------------|-------------------------------------|
| ``num_processes()`` (mpi_tools.py:5-9)  | ``num_processes()``/``num_devices``|
| ``mpi_all_reduce`` (mpi_tools.py:12-16) | ``all_reduce`` → ``lax.psum`` etc.  |
| ``mpi_sum`` (mpi_tools.py:19-27)        | ``all_reduce(x, 'sum', axis)``      |
| ``mpi_avg_grads`` (mpi_tools.py:30-37)  | ``avg_grads`` → one fused ``pmean`` |
| ``mpi_broadcast`` (mpi_tools.py:40-44)  | ``broadcast_from`` (device 0)       |
| ``sync_params`` (mpi_tools.py:47-53)    | ``sync_params``                     |

Where the reference issues ~62 blocking per-tensor ``Allreduce`` calls per
step with numpy staging copies (one per parameter, ``mpi_tools.py:34-37``),
``avg_grads`` is a single traced ``pmean`` over the whole gradient pytree —
XLA fuses it into the backward pass and schedules it on the ICI concurrently
with remaining compute.

Beyond the reference's surface: ``all_gather`` (tiled Allgather) and
``reduce_scatter_mean`` (ReduceScatter/P) are the two halves of the
ZeRO-sharded weight update (train/step.py ``zero_opt_state``) — the
reference's MPI wrapper never needed them because every rank kept a full
optimizer replica.

These functions must run inside an SPMD context that binds the axis name
(``shard_map`` over a mesh, or ``jit``-of-``shard_map``). Under plain
auto-sharded ``jit`` they are unnecessary: replication + XLA's partitioner
insert the equivalent collectives automatically.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax


def num_processes() -> int:
    """World size — host processes (≙ MPI ranks for multi-host launch)."""
    return jax.process_count()


def num_devices() -> int:
    """Total chips — the DP world size in the single-controller model."""
    return jax.device_count()


def all_reduce(x: Any, op: str = "sum", axis: str = "data") -> Any:
    """Pytree allreduce (≙ ``mpi_all_reduce``/``mpi_sum``, mpi_tools.py:12-27)."""
    reducer = {"sum": lax.psum, "mean": lax.pmean, "max": lax.pmax, "min": lax.pmin}[op]
    return jax.tree_util.tree_map(lambda v: reducer(v, axis), x)


def avg_grads(grads: Any, axis: str = "data") -> Any:
    """Average a gradient pytree across the data axis — the entire
    ``mpi_avg_grads`` stack (mpi_tools.py:30-37) as one fused collective."""
    return jax.tree_util.tree_map(lambda g: lax.pmean(g, axis), grads)


def all_gather(x: Any, axis: str = "data") -> Any:
    """Pytree tiled allgather over ``axis``: per-shard ``[n, ...]`` blocks →
    the concatenated ``[P*n, ...]`` array on EVERY shard (≙ MPI Allgather on
    device data). This is the reassembly half of the ZeRO-sharded weight
    update (train/step.py, ``zero_opt_state``): each shard applies the
    optimizer to its 1/P parameter slice, then one allgather rebuilds the
    full parameter tree for the next forward."""
    return jax.tree_util.tree_map(
        lambda v: lax.all_gather(v, axis, tiled=True), x
    )


def reduce_scatter_mean(x: Any, axis: str = "data") -> Any:
    """Pytree reduce-scatter-mean over ``axis``: each leaf must carry a
    leading dimension divisible by the axis size; shard k receives block k of
    the cross-shard MEAN (``psum_scatter / P`` — exactly slice k of what
    ``pmean`` would hand every shard, at 1/P the egress bytes). The ZeRO
    gradient path (train/step.py): with the optimizer state sharded, each
    shard only ever *needs* its own gradient slice, so the grad collective
    halves from allreduce to reduce-scatter."""
    size = lax.psum(1, axis)
    return jax.tree_util.tree_map(
        lambda v: lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
        / size,
        x,
    )


def broadcast_from(x: Any, axis: str = "data", root: int = 0) -> Any:
    """Broadcast root's values to all shards (≙ ``mpi_broadcast``,
    mpi_tools.py:40-44). Implemented as a masked psum: only root contributes."""
    idx = lax.axis_index(axis)

    def bcast(v):
        contrib = jnp.where(idx == root, v, jnp.zeros_like(v))
        return lax.psum(contrib, axis)

    return jax.tree_util.tree_map(bcast, x)


def sync_params(params: Any, axis: str = "data", root: int = 0) -> Any:
    """Make every shard hold root's parameters (≙ ``sync_params``,
    mpi_tools.py:47-53). Under replicated-sharding jit this is the identity —
    replication is maintained by the compiler; kept for SPMD-explicit code
    and for repairing divergence after per-shard mutation."""
    return broadcast_from(params, axis=axis, root=root)


def host_allgather(values) -> "Any":
    """HOST-side allgather of a small per-process f32 vector: ``[k]`` on each
    process → ``[process_count, k]`` on every process, row p = process p's
    contribution (≙ ``comm.allgather`` — the one reference collective with no
    in-step equivalent here, because auto-partitioned jit never needs it).

    This is the telemetry exchange path, with two consumers: the step-time
    heartbeat (``obs/heartbeat.py``) and the metrics-registry cross-host
    merge (``obs/metrics.py MetricsRegistry.merged`` — counters/histogram
    buckets sum, gauges max, one flat vector per process). Rows are a few
    floats per host, NOT tensors — the device hop is one tiny collective
    over the same ICI/DCN fabric as the gradient all-reduce. Every process
    must call it at the same point (it is a collective; the trainer
    snapshots the registry on a step-count cadence for exactly that
    reason); single-process is the identity with a leading axis."""
    import numpy as np

    vals = np.atleast_1d(np.asarray(values, np.float32))
    if jax.process_count() == 1:
        return vals[None]
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(vals))
