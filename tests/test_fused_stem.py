"""Pin the fused stem kernel (ops/fused_stem.py) to the unfused XLA
composition it replaces — values AND gradients, via the Pallas interpreter
on CPU (the same kernel code path the TPU compiles).

Reference semantics: ``max_pool3x3s2p1(relu(y·a + b))`` with f32 math
(≙ the torchvision resnet stem tail, reference ``models.py:30-45``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.ops.fused_stem import (
    _reference_impl,
    stem_affine_relu_pool,
)

B, H, W, C = 4, 16, 16, 64


def _inputs(rng, tie_heavy=False, dtype=jnp.float32):
    y = rng.standard_normal((B, H, W, C)).astype(np.float32)
    if tie_heavy:
        # Quantize hard so pool windows tie constantly (and relu produces
        # exact-zero plateaus) — the select-and-scatter tie-break regime.
        y = np.round(y * 2) / 2
    a = (0.5 + rng.random(C)).astype(np.float32)
    b = rng.standard_normal(C).astype(np.float32) * 0.1
    return jnp.asarray(y, dtype), jnp.asarray(a), jnp.asarray(b)


@pytest.mark.parametrize("tie_heavy", [False, True])
def test_forward_matches_reference(rng, tie_heavy):
    y, a, b = _inputs(rng, tie_heavy)
    got = stem_affine_relu_pool(y, a, b, interpret=True)
    want = _reference_impl(y, a, b)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("tie_heavy", [False, True])
def test_gradients_match_reference(rng, tie_heavy):
    y, a, b = _inputs(rng, tie_heavy)
    co = jnp.asarray(rng.standard_normal((B, H // 2, W // 2, C)), jnp.float32)

    def loss(fn):
        return lambda y, a, b: jnp.sum(fn(y, a, b) * co)

    gy, ga, gb = jax.grad(
        loss(lambda y, a, b: stem_affine_relu_pool(y, a, b, interpret=True)),
        argnums=(0, 1, 2),
    )(y, a, b)
    ry, ra, rb = jax.grad(loss(_reference_impl), argnums=(0, 1, 2))(y, a, b)
    np.testing.assert_allclose(gy, ry, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ga, ra, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(gb, rb, rtol=1e-5, atol=1e-4)


def test_bf16_storage_roundtrip(rng):
    """Production dtype: bf16 in/out, f32 compute inside the kernel."""
    y, a, b = _inputs(rng, dtype=jnp.bfloat16)
    got = stem_affine_relu_pool(y, a, b, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _reference_impl(y, a, b)
    np.testing.assert_allclose(
        got.astype(np.float32), want.astype(np.float32), rtol=2e-2, atol=2e-2
    )


def _bf16_pool_reference(y, a, b):
    """XLA reference for the MPT_STEM_BF16_POOL lever: pooling over the
    bf16-ROUNDED post-relu activations. Rounding is monotone (a ≥ b ⇒
    bf16(a) ≥ bf16(b)), so the window winner and reduce_window's row-major
    first-match tie semantics transfer exactly — value AND gradient
    routing are pinned tightly against this, not loosely against f32.

    The rounding is STRAIGHT-THROUGH (stop_gradient) to mirror the kernel
    exactly: bf16 values pick the winner, but the backward routes the
    FULL-PRECISION f32 cotangent — a plain .astype chain would instead
    bf16-round the cotangent sums at positions winning several windows."""
    from jax import lax

    from mpi_pytorch_tpu.ops.fused_stem import nn_max_pool_f32

    z = jax.nn.relu(y.astype(jnp.float32) * a + b)
    z = z + lax.stop_gradient(
        z.astype(jnp.bfloat16).astype(jnp.float32) - z
    )
    return nn_max_pool_f32(z).astype(y.dtype)


_LEVERS = [
    # (env, value, reference): the §4d byte-bound lever gates. bf16
    # pooling is pinned against the bf16-rounded reference (see above);
    # the other three are exact re-tilings pinned against the f32 one.
    ("MPT_STEM_BF16_POOL", "1", _bf16_pool_reference),
    ("MPT_STEM_LANES", "256", _reference_impl),
    ("MPT_STEM_IDX_INT8", "1", _reference_impl),
    ("MPT_STEM_C_BLOCK", "16", _reference_impl),
]


@pytest.mark.parametrize("env,val,reference", _LEVERS)
@pytest.mark.parametrize("tie_heavy", [False, True])
def test_levers_match_reference(rng, monkeypatch, env, val, reference, tie_heavy):
    """Each §4d byte-bound lever (docs/RESULTS.md) preserves its reference
    semantics — values AND all three gradients — through the real kernel
    code path. The lever config is read from the env at trace time, so the
    monkeypatched env drives the actual gated kernel variant. B=256 so the
    256-lane lever genuinely widens the batch block."""
    monkeypatch.setenv(env, val)
    y = rng.standard_normal((256, 8, 8, C)).astype(np.float32)
    if tie_heavy:
        y = np.round(y * 2) / 2
    y = jnp.asarray(y)
    # Power-of-two scales make y·a EXACT, so a+b is the affine's only f32
    # rounding and FMA ≡ mul+add — otherwise the kernel's and the XLA
    # reference's 1-ulp f32 contraction differences land on bf16 rounding
    # boundaries and the bf16-pool comparison sees spurious bf16-ulp flips.
    a = jnp.asarray(2.0 ** rng.integers(-1, 2, C).astype(np.float32))
    b = jnp.asarray(
        (rng.standard_normal(C).astype(np.float32) * 0.1)
        .astype(jnp.bfloat16)
        .astype(np.float32)
    )
    got = stem_affine_relu_pool(y, a, b, interpret=True)
    want = reference(y, a, b)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    # Cotangent on the bf16 grid: the bf16 reference's VJP rounds the
    # cotangent through its cast (the kernel back-propagates full f32), so
    # a bf16-exact cotangent makes the comparison rounding-free.
    co = (
        jnp.asarray(rng.standard_normal((256, 4, 4, C)), jnp.float32)
        .astype(jnp.bfloat16)
        .astype(jnp.float32)
    )

    def loss(fn):
        return lambda y, a, b: jnp.sum(fn(y, a, b) * co)

    g = jax.grad(
        loss(lambda y, a, b: stem_affine_relu_pool(y, a, b, interpret=True)),
        argnums=(0, 1, 2),
    )(y, a, b)
    r = jax.grad(loss(reference), argnums=(0, 1, 2))(y, a, b)
    for u, v in zip(g, r):
        np.testing.assert_allclose(u, v, rtol=1e-5, atol=1e-4)


def test_idx_int8_lever_changes_residual_dtype(rng, monkeypatch):
    """The int8-argmax lever must actually store int8 (the HBM-traffic
    halving is the point) — pinned on the fwd-with-idx output directly."""
    from mpi_pytorch_tpu.ops.fused_stem import _fwd_impl

    y, a, b = _inputs(rng)
    yt = jnp.transpose(y, (1, 2, 3, 0))
    _, idx = _fwd_impl(
        yt, a, b, want_idx=True, interpret=True
    )
    assert idx.dtype == jnp.bfloat16  # default storage
    monkeypatch.setenv("MPT_STEM_IDX_INT8", "1")
    _, idx8 = _fwd_impl(yt, a, b, want_idx=True, interpret=True)
    assert idx8.dtype == jnp.int8
    np.testing.assert_array_equal(
        np.asarray(idx, np.int32), np.asarray(idx8, np.int32)
    )


def test_shard_map_multi_device_matches_single_call(rng):
    """dp_mesh partitions the kernel over the 8-device data axis: values
    and all three gradients equal the reference (the da/db cotangents are
    psum-reduced across shards by shard_map's transpose)."""
    from jax.sharding import Mesh

    n = len(jax.devices())
    assert n == 8  # conftest virtual-CPU mesh
    mesh = Mesh(np.array(jax.devices()).reshape(n, 1), ("data", "model"))
    y = jnp.asarray(rng.standard_normal((2 * n, H, W, C)), jnp.float32)
    a = jnp.asarray((0.5 + rng.random(C)).astype(np.float32))
    b = jnp.asarray(rng.standard_normal(C).astype(np.float32) * 0.1)
    got = stem_affine_relu_pool(y, a, b, interpret=True, dp_mesh=mesh)
    want = _reference_impl(y, a, b)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    co = jnp.asarray(rng.standard_normal((2 * n, H // 2, W // 2, C)), jnp.float32)

    def loss(fn):
        return lambda y, a, b: jnp.sum(fn(y, a, b) * co)

    g = jax.grad(
        loss(lambda y, a, b: stem_affine_relu_pool(
            y, a, b, interpret=True, dp_mesh=mesh
        )),
        argnums=(0, 1, 2),
    )(y, a, b)
    r = jax.grad(loss(_reference_impl), argnums=(0, 1, 2))(y, a, b)
    np.testing.assert_allclose(g[0], r[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g[1], r[1], rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(g[2], r[2], rtol=1e-5, atol=1e-4)

    # An indivisible batch must take the XLA path (never replicate the
    # Mosaic call), still producing reference values.
    y_odd = y[: 2 * n - 1]
    got_odd = stem_affine_relu_pool(y_odd, a, b, interpret=True, dp_mesh=mesh)
    np.testing.assert_allclose(
        got_odd, _reference_impl(y_odd, a, b), rtol=1e-6, atol=1e-6
    )


def test_shape_guards(rng):
    y, a, b = _inputs(rng)
    with pytest.raises(ValueError):
        stem_affine_relu_pool(y[:, :15], a, b, interpret=True)
    with pytest.raises(ValueError):
        stem_affine_relu_pool(y, a[:3], b, interpret=True)


def test_module_runs_kernel_under_env_gate(rng, monkeypatch):
    """MPT_STEM_INTERPRET routes the module through the REAL kernel code
    path (Pallas interpreter) instead of the XLA fallback — the gate the
    whole-model CPU tests rely on."""
    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    from mpi_pytorch_tpu.models.common import FusedStemBNReluPool

    y = jnp.asarray(rng.standard_normal((B, H, W, C)), jnp.float32)
    m = FusedStemBNReluPool()
    v = m.init(jax.random.PRNGKey(0), y, True)
    out, _ = m.apply(v, y, False, mutable=["batch_stats"])
    monkeypatch.delenv("MPT_STEM_INTERPRET")
    want = m.apply(v, y, False, mutable=["batch_stats"])[0]
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_fused_stem_training_matches_unfused(rng, monkeypatch, tmp_path):
    """TWO full sharded training epochs through the REAL kernel code path
    (Pallas interpreter) equal the unfused stem's epochs — the end-to-end
    integration pin: custom-VJP grads, BN stat updates, optimizer steps,
    checkpointing, all through the trainer."""
    import os

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.train.trainer import train

    def cfg(fused, sub):
        c = Config(
            model_name="resnet18", num_classes=200, batch_size=16,
            num_epochs=2, debug=True, debug_sample_size=64,
            synthetic_data=True, compute_dtype="float32",
            width=32, height=32, fused_stem=fused, validate=False,
            loader_workers=2, log_every_steps=0, metrics_file="",
            checkpoint_dir=os.path.join(str(tmp_path), sub),
            log_file=os.path.join(str(tmp_path), sub + ".log"),
        )
        c.validate_config()
        return c

    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    fused = train(cfg(True, "f"))
    monkeypatch.delenv("MPT_STEM_INTERPRET")
    plain = train(cfg(False, "p"))
    # Same data, same init, same seeds. Epoch 1 agrees to float tolerance;
    # later epochs drift at the usual chaotic-amplification rate of
    # correct-but-not-bit-identical op orderings (measured: 1e-6 after
    # epoch 1, 1e-3 after epoch 2) — gradient EXACTNESS is pinned tightly
    # in test_gradients_match_reference; this test pins the integration.
    np.testing.assert_allclose(
        fused.epoch_losses[:1], plain.epoch_losses[:1], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        fused.epoch_losses, plain.epoch_losses, rtol=1e-2, atol=1e-2
    )


def test_spmd_fused_stem_training_matches_unfused(rng, monkeypatch, tmp_path):
    """The multi-chip recipe, pinned (VERDICT r5 #3): ``--spmd-mode`` +
    ``--fused-stem`` on the 8-device CPU mesh, REAL kernel code path
    (Pallas interpreter), epoch losses ≡ the unfused spmd run. In spmd
    mode the step itself is a shard_map handing the kernel PER-SHARD
    batches (the trainer passes no dp_mesh), so this drives exactly the
    partitioned regime the kernel sees on a multi-chip pod.

    Batch 64 → 8 images per shard: at per-shard batch 2 the folded affine's
    float rounding near relu boundaries, amplified by noisy 2-image local-BN
    variances, drifts the trajectories ~1e-2 (measured; same equivalence
    class the auto-mode test tolerates at later epochs) — 8/shard is both
    the realistic regime and numerically tight."""
    import os

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.train.trainer import train

    def cfg(fused, sub):
        c = Config(
            model_name="resnet18", num_classes=200, batch_size=64,
            num_epochs=2, debug=True, debug_sample_size=128,
            synthetic_data=True, compute_dtype="float32",
            width=32, height=32, fused_stem=fused, spmd_mode=True,
            validate=False, loader_workers=2, log_every_steps=0,
            metrics_file="",
            checkpoint_dir=os.path.join(str(tmp_path), sub),
            log_file=os.path.join(str(tmp_path), sub + ".log"),
        )
        c.validate_config()
        return c

    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    fused = train(cfg(True, "sf"))
    monkeypatch.delenv("MPT_STEM_INTERPRET")
    plain = train(cfg(False, "sp"))
    np.testing.assert_allclose(
        fused.epoch_losses[:1], plain.epoch_losses[:1], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        fused.epoch_losses, plain.epoch_losses, rtol=1e-2, atol=1e-2
    )


def test_module_matches_unfused_stem(rng):
    """FusedStemBNReluPool ≡ batch_norm → relu → max_pool(3,2,1): same
    output, same batch_stats update, same eval-mode behavior, and the
    SAME variable tree (checkpoints interchange)."""
    from flax import linen as nn

    from mpi_pytorch_tpu.models.common import (
        FusedStemBNReluPool,
        batch_norm,
        max_pool,
    )

    class Unfused(nn.Module):
        @nn.compact
        def __call__(self, y, use_running_average):
            z = batch_norm("bn1")(y, use_running_average=use_running_average)
            return max_pool(nn.relu(z), 3, 2, padding=1)

    class Fused(nn.Module):
        @nn.compact
        def __call__(self, y, use_running_average):
            return FusedStemBNReluPool(name="bn1")(y, use_running_average)

    y = jnp.asarray(rng.standard_normal((B, H, W, C)), jnp.float32)
    uf, fu = Unfused(), Fused()
    vu = uf.init(jax.random.PRNGKey(0), y, True)
    vf = fu.init(jax.random.PRNGKey(0), y, True)
    assert jax.tree.structure(vu) == jax.tree.structure(vf)

    # Train mode: same output, same running-stat update (from shared params).
    ou, su = uf.apply(vu, y, False, mutable=["batch_stats"])
    of, sf = fu.apply(vu, y, False, mutable=["batch_stats"])
    np.testing.assert_allclose(ou, of, rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda x, z: np.testing.assert_allclose(x, z, rtol=1e-5, atol=1e-6),
        su["batch_stats"], sf["batch_stats"],
    )

    # Eval mode: running stats drive both identically.
    eu = uf.apply(vu, y, True)
    ef = fu.apply(vu, y, True)
    np.testing.assert_allclose(eu, ef, rtol=1e-5, atol=1e-5)

    # Gradients through the module (params + input) agree.
    def tloss(m):
        def f(params, y):
            out, _ = m.apply(
                {"params": params, "batch_stats": vu["batch_stats"]},
                y, False, mutable=["batch_stats"],
            )
            return jnp.sum(out * out)
        return f

    gu = jax.grad(tloss(uf), argnums=(0, 1))(vu["params"], y)
    gf = jax.grad(tloss(fu), argnums=(0, 1))(vu["params"], y)
    jax.tree.map(
        lambda x, z: np.testing.assert_allclose(x, z, rtol=1e-4, atol=1e-4),
        gu, gf,
    )


def test_densenet_fused_stem_matches_unfused(rng, monkeypatch):
    """densenet121's stem (features.conv0..pool0) is geometrically the
    resnet stem, so the fused kernel applies (verdict r5 #7): a whole
    DenseNet forward with fused_stem=True — real kernel code path via the
    interpreter — equals the unfused model on the SAME variables (the
    variable trees are identical, so checkpoints interchange), and the
    param gradients agree."""
    from mpi_pytorch_tpu.models.densenet import DenseNet

    kw = dict(block_config=(1, 1), num_classes=5, growth_rate=8,
              num_init_features=64)
    unfused = DenseNet(**kw)
    fused = DenseNet(fused_stem=True, **kw)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)

    monkeypatch.setenv("MPT_STEM_INTERPRET", "1")
    vu = unfused.init({"params": jax.random.PRNGKey(0)}, x, train=True)
    vf = fused.init({"params": jax.random.PRNGKey(0)}, x, train=True)
    assert jax.tree.structure(vu) == jax.tree.structure(vf)

    ou, su = unfused.apply(vu, x, train=True, mutable=["batch_stats"])
    of, sf = fused.apply(vu, x, train=True, mutable=["batch_stats"])
    np.testing.assert_allclose(ou, of, rtol=1e-5, atol=1e-5)
    jax.tree.map(
        lambda p, q: np.testing.assert_allclose(p, q, rtol=1e-5, atol=1e-6),
        su["batch_stats"], sf["batch_stats"],
    )

    def tloss(m):
        def f(params):
            out, _ = m.apply(
                {"params": params, "batch_stats": vu["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return jnp.sum(out * out)
        return f

    gu = jax.grad(tloss(unfused))(vu["params"])
    gf = jax.grad(tloss(fused))(vu["params"])
    jax.tree.map(
        lambda p, q: np.testing.assert_allclose(p, q, rtol=1e-4, atol=1e-4),
        gu, gf,
    )


def test_densenet_fused_stem_registry_and_default():
    """densenet121 is fused-stem CAPABLE (--fused-stem builds it) but NOT a
    bench default until its chip A/B lands (docs/RESULTS.md §4: stem tail
    ≈3% of its roofline bound — the fused-head discipline)."""
    from mpi_pytorch_tpu.models.registry import (
        FUSED_STEM_MODELS,
        MEASURED_FUSED_STEM_MODELS,
        initialize_model,
    )

    assert "densenet121" in FUSED_STEM_MODELS
    assert "densenet121" not in MEASURED_FUSED_STEM_MODELS
    model, _ = initialize_model("densenet121", 5, fused_stem=True)
    assert model.fused_stem
    # fused_stem_default is platform-gated (TPU); on the CPU test mesh it
    # must be False for every model regardless of the measured tuple.
    from mpi_pytorch_tpu.models.registry import fused_stem_default

    assert not fused_stem_default("densenet121")
    assert not fused_stem_default("resnet18")

    from mpi_pytorch_tpu.config import parse_config

    cfg = parse_config(["--model-name", "densenet121", "--fused-stem", "1"])
    assert cfg.fused_stem
