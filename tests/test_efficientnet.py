"""EfficientNet-B0: torchvision-exact parameter count, SE/MBConv structure,
stochastic depth determinism in eval, and a loss-decreasing train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.models import create_model_bundle

# The whole module rides the expensive session-scoped model-zoo
# compile (or end-to-end trainer runs): core-suite runs skip it
# (pytest -m 'not slow').
pytestmark = pytest.mark.slow


def test_efficientnet_param_count_matches_torchvision():
    """5,288,548 params at 1000 classes — torchvision efficientnet_b0's exact
    count (BN running stats live in batch_stats, not params)."""
    bundle, variables = create_model_bundle(
        "efficientnet_b0", 1000, rng=jax.random.PRNGKey(0), image_size=64
    )
    got = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    assert got == 5_288_548


def test_efficientnet_forward_and_structure():
    bundle, variables = create_model_bundle(
        "efficientnet_b0", 10, rng=jax.random.PRNGKey(0), image_size=64
    )
    params = variables["params"]
    # 16 MBConv blocks; block0 (expand=1) has no expand conv but has SE.
    assert sum(1 for k in params if k.startswith("block")) == 16
    assert "expand" not in params["block0"] and "se" in params["block0"]
    # SE squeeze width = block INPUT channels / 4 (not the expanded width):
    # block1 input is 16ch -> squeeze 4, operating on the 96ch expansion.
    assert params["block1"]["se"]["reduce"]["kernel"].shape == (1, 1, 96, 4)
    # 5x5 depthwise kernels appear in the (6,40,2,2,5) stage (blocks 3-4).
    assert params["block3"]["depthwise"]["kernel"].shape[:2] == (5, 5)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    logits = bundle.model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    # Eval mode is deterministic (stochastic depth and dropout disabled).
    logits2 = bundle.model.apply(variables, x, train=False)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))


def test_efficientnet_trains_through_standard_step():
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import make_train_step

    bundle, variables = create_model_bundle(
        "efficientnet_b0", 10, rng=jax.random.PRNGKey(0), image_size=32
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(1e-3), rng=jax.random.PRNGKey(1),
    )
    rng = np.random.default_rng(2)
    images = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 8), jnp.int32)
    step = make_train_step(jnp.float32)
    losses = []
    for _ in range(4):
        state, metrics = step(state, (images, labels))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert state.batch_stats is not None  # BN model: running stats updated
