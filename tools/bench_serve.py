"""Load-drive the online inference server: latency percentiles vs load.

Two canonical load shapes, both per (bucket set, max_wait_ms) sweep point:

- **closed loop**: N client threads in a submit→wait→repeat cycle — the
  saturation throughput shape (offered load adapts to service rate).
- **open loop**: seeded-Poisson arrivals at a fixed offered RPS — the SLO
  shape (latency vs offered load, with typed rejections counted instead
  of silently queueing unbounded). Open-loop numbers are the honest ones
  for "can it hold X req/s at Y ms p99" (closed-loop coordinated omission
  hides queueing).

Each run prints ONE ``kind="serve_bench"`` JSONL row (p50/p95/p99 latency,
images/sec, mean batch fill, rejected count, compiles-after-warmup — which
must be 0, the serve subsystem's defining invariant). Rows validate
against ``mpi_pytorch_tpu/obs/schema.py``; the committed artifact is
``docs/serve_bench.json`` (``tools/summarize_benches.py`` renders it).

``--smoke`` is the CPU tier-1 mode: tiny model, two bucket sets, closed +
open loop, seconds not minutes — the shape of the measurement, not a
number worth quoting. Chip rows are staged per the artifact discipline
(docs/RESULTS.md staleness ledger) until a driver-confirmed TPU battery
refreshes them.

``--fleet N`` drives the same sweeps against a local N-host FLEET
(threads on the CPU/host mesh, one shared executable set) through the
load-aware router (``serve/fleet/``): rejections are the front door's
admission control, and each row gains ``fleet_hosts`` plus a ``per_host``
fill/latency breakdown from the hosts' registry snapshots — how evenly
the router actually spread the load.

``--precision bf16,int8`` sweeps the serving precision (ISSUE 11): both
values build ONE server holding both startup-compiled executable sets
and switch live between them (``set_precision`` — the same no-compile
lever the fleet controller retunes), so the bf16 and int8 points share
params, warmup, and load shape. Rows carry ``precision``, and int8 rows
carry ``parity_top1`` — the startup int8-vs-bf16 top-1 agreement the
throughput claim is conditioned on.

``--models resnet18,mobilenet_v2`` turns the sweep multi-tenant
(ISSUE 14): ONE zoo server/fleet holds every tenant's executable sets,
the load driver interleaves per-tenant traffic from a seeded assignment
sequence (``--hot-model X`` skews 80% onto one tenant — the starvation
drill), and every sweep point yields one row PER TENANT (model-keyed
p99/fill/rejected columns, ``load_shape`` stamped). ``model`` +
``load_shape`` key into ``check_regression``'s serve trend-line
identity, so tenant rows never compare cross-model or cross-shape.

``--replay <trace>`` (ISSUE 18) swaps the synthetic load for a RECORDED
one: the fleet trace's ``route/request`` roots are extracted into a
fingerprinted workload artifact (``obs/replay.py``) and their exact
arrival process is re-driven against the candidate config, over any
transport. Rows stamp ``mode="replay"``, the workload fingerprint (its
own regression trend line — never compared against synthetic Poisson),
and ``replay_diff`` — the recorded-vs-replayed per-phase differential
report. Record with ``--fleet N --trace-sample-rate 1.0
--fleet-trace-file t.jsonl``; replay with ``--replay t.jsonl``
(``--speed``/``--replay-window`` warp and trim, changing the
fingerprint).

Run: ``python tools/bench_serve.py --smoke [--out docs/serve_bench.json]``
     ``python tools/bench_serve.py --bucket-sets "1,8,32,128;1,32,512" \
        --max-wait-ms 2,5,10 --requests 2000 --rps 0,500,2000``
     ``python tools/bench_serve.py --smoke --fleet 3``
     ``python tools/bench_serve.py --smoke --precision bf16,int8``
     ``python tools/bench_serve.py --smoke --fleet 2 \
        --models resnet18,mobilenet_v2 [--hot-model resnet18]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentiles(lat_ms: list[float]) -> dict:
    if not lat_ms:
        # A fully-rejected sweep point (offered load >> capacity with a
        # small queue) is a VALID result — the row must report rejected=N,
        # not crash the sweep on an empty percentile.
        return {"p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}
    arr = np.asarray(lat_ms, np.float64)
    return {
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p95_ms": round(float(np.percentile(arr, 95)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
    }


def _image_pool(n: int, size: tuple[int, int], seed: int) -> list[np.ndarray]:
    """Distinct uint8 request images (raw pixels, so the server's
    preprocess pool does real normalize work per request)."""
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, size=(*size, 3)).astype(np.uint8) for _ in range(n)
    ]


def closed_loop(server, pool, requests: int, concurrency: int, timeout_s: float):
    """N clients in submit→wait→repeat; returns (latencies_ms, wall_s, rejected)."""
    lat_ms: list[float] = []
    rejected = [0]
    lock = threading.Lock()
    counter = [0]

    from mpi_pytorch_tpu.serve import QueueFullError

    def client() -> None:
        while True:
            with lock:
                i = counter[0]
                if i >= requests:
                    return
                counter[0] += 1
            t0 = time.monotonic()
            try:
                server.submit(pool[i % len(pool)]).result(timeout=timeout_s)
            except QueueFullError:
                with lock:
                    rejected[0] += 1
                continue
            dt = 1e3 * (time.monotonic() - t0)
            with lock:
                lat_ms.append(dt)

    t0 = time.monotonic()
    threads = [threading.Thread(target=client) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lat_ms, time.monotonic() - t0, rejected[0]


def open_loop(server, pool, requests: int, rps: float, seed: int, timeout_s: float):
    """Seeded-Poisson arrivals at ``rps``; latency measured per request
    from its (intended) submit; full-queue submissions count as rejected.

    The client HONORS the rejection's ``retry_after_ms`` hint (ISSUE 12
    satellite): after a hinted 429/QueueFullError, no submission goes out
    before the hint expires — arrivals due inside the backoff window are
    deferred to its edge (still counted at their deferred submit time),
    instead of hammering a host that just said "not before T". A
    saturated sweep point therefore measures the BACKPRESSURE PROTOCOL's
    throughput, not a retry storm's."""
    from mpi_pytorch_tpu.serve import QueueFullError

    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rps, size=requests)
    lat_ms: list[float] = []
    lock = threading.Lock()
    futures = []
    rejected = 0
    backoff_until = 0.0
    t0 = time.monotonic()
    next_t = t0
    for i in range(requests):
        next_t += gaps[i]
        if next_t < backoff_until:
            next_t = backoff_until  # defer to the hint's edge, don't hammer
        delay = next_t - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t_submit = time.monotonic()
        try:
            fut = server.submit(pool[i % len(pool)])
        except QueueFullError as e:
            rejected += 1
            if e.retry_after_ms:
                backoff_until = max(
                    backoff_until, time.monotonic() + e.retry_after_ms / 1e3
                )
            continue

        def _done(f, t_submit=t_submit):
            dt = 1e3 * (time.monotonic() - t_submit)
            with lock:
                lat_ms.append(dt)

        fut.add_done_callback(_done)
        futures.append(fut)
    for f in futures:
        f.result(timeout=timeout_s)
    return lat_ms, time.monotonic() - t0, rejected


def _delta_mean(snap1, snap0, hist_name):
    """Mean of a registry histogram over THIS sweep point only: the
    sketches are cumulative across a server's life, so per-point means
    come from (sum, count) deltas (percentiles cannot be delta'd from
    summaries — the per-point tail is the top-level row's job)."""
    h1 = snap1.get("histograms", {}).get(hist_name) or {}
    h0 = snap0.get("histograms", {}).get(hist_name) or {}
    n = h1.get("count", 0) - h0.get("count", 0)
    if n <= 0:
        return None
    return round((h1.get("sum", 0.0) - h0.get("sum", 0.0)) / n, 3)


def _per_host_breakdown(snaps0, snaps1, stats0, stats1) -> dict:
    """The --fleet rows' per-host fill/latency table — all values are
    deltas over this sweep point (a host promoted mid-point, e.g. the
    spare after a failover, diffs against empty)."""
    out = {}
    for name, snap in sorted(snaps1.items()):
        snap0 = snaps0.get(name, {})
        served0 = stats0["hosts"].get(name, {}).get("served", 0)
        served1 = stats1["hosts"].get(name, {}).get("served", 0)
        out[name] = {
            "requests": served1 - served0,
            "fill_pct": _delta_mean(snap, snap0, "serve/fill_pct"),
            "mean_ms": _delta_mean(
                snap, snap0, "serve/request_latency_ms"
            ),
        }
    return out


def run_point_tenants(server, pool, models, weights, *, mode, requests,
                      concurrency, rps, seed, timeout_s, fleet_hosts=0,
                      load_shape="uniform"):
    """Multi-tenant sweep point (ISSUE 14): one seeded tenant-assignment
    sequence drives interleaved traffic across ``models`` (weighted —
    the hot-tenant skewed shape), latencies/rejections tally PER TENANT,
    and the point yields one ``serve_bench`` row per tenant (p99 / fill /
    rejected columns each under its ``model`` key).

    Open-loop arrivals for a tenant inside its own ``retry_after_ms``
    backoff window are SHED client-side (counted rejected) — per-tenant
    backpressure must not distort the other tenants' arrival process."""
    from mpi_pytorch_tpu.serve import QueueFullError

    rng = np.random.default_rng(seed)
    assign = rng.choice(len(models), size=requests, p=weights)
    stats0 = server.tenant_stats()
    lat = {m: [] for m in models}
    rejected = {m: 0 for m in models}
    lock = threading.Lock()

    if mode == "open":
        gaps = rng.exponential(1.0 / rps, size=requests)
        backoff_until = {m: 0.0 for m in models}
        futures = []
        t0 = time.monotonic()
        next_t = t0
        for i in range(requests):
            model = models[int(assign[i])]
            next_t += gaps[i]
            delay = next_t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if time.monotonic() < backoff_until[model]:
                rejected[model] += 1  # shed: the tenant said "not yet"
                continue
            t_submit = time.monotonic()
            try:
                fut = server.submit(pool[i % len(pool)], model=model)
            except QueueFullError as e:
                rejected[model] += 1
                if e.retry_after_ms:
                    backoff_until[model] = max(
                        backoff_until[model],
                        time.monotonic() + e.retry_after_ms / 1e3,
                    )
                continue

            def _done(f, m=model, t_submit=t_submit):
                dt = 1e3 * (time.monotonic() - t_submit)
                with lock:
                    lat[m].append(dt)

            fut.add_done_callback(_done)
            futures.append(fut)
        for f in futures:
            f.result(timeout=timeout_s)
        wall = time.monotonic() - t0
    else:
        counter = [0]

        def client() -> None:
            while True:
                with lock:
                    i = counter[0]
                    if i >= requests:
                        return
                    counter[0] += 1
                model = models[int(assign[i])]
                t_submit = time.monotonic()
                try:
                    server.submit(
                        pool[i % len(pool)], model=model
                    ).result(timeout=timeout_s)
                except QueueFullError:
                    with lock:
                        rejected[model] += 1
                    continue
                dt = 1e3 * (time.monotonic() - t_submit)
                with lock:
                    lat[model].append(dt)

        t0 = time.monotonic()
        threads = [threading.Thread(target=client) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0

    stats1 = server.tenant_stats()
    compiles = server.stats()["compiles_after_warmup"]
    rows = []
    for m in models:
        s0, s1 = stats0.get(m, {}), stats1.get(m, {})
        served = s1.get("served", 0) - s0.get("served", 0)
        padded = s1.get("padded_rows", 0) - s0.get("padded_rows", 0)
        fill = served / (served + padded) if served + padded else 0.0
        share = float(weights[models.index(m)])
        rows.append({
            "kind": "serve_bench",
            "ts": time.time(),
            "mode": mode,
            "model": m,
            "load_shape": load_shape,
            "requests": len(lat[m]),
            "rejected": rejected[m],
            "offered_rps": round(rps * share, 1) if mode == "open" else None,
            "images_per_sec": (
                round(len(lat[m]) / wall, 1) if wall > 0 else 0.0
            ),
            "mean_fill_ratio": round(fill, 4),
            "compiles_after_warmup": compiles,
            **_percentiles(lat[m]),
        })
        if fleet_hosts:
            rows[-1]["fleet_hosts"] = fleet_hosts
    return rows


def _sum_host_stat(stats: dict, key: str) -> int:
    """Sum ``key`` across a fleet's per-host stats (or read it straight
    off a single server's stats)."""
    if "hosts" in stats:
        return sum(s.get(key, 0) for s in stats["hosts"].values())
    return stats.get(key, 0)


def run_point(server, pool, *, mode, requests, concurrency, rps, seed, timeout_s,
              fleet_hosts=0):
    stats0 = server.stats()
    snaps0 = server.host_snapshots() if fleet_hosts else None
    if mode == "open":
        lat_ms, wall, rejected = open_loop(
            server, pool, requests, rps, seed, timeout_s
        )
    else:
        lat_ms, wall, rejected = closed_loop(
            server, pool, requests, concurrency, timeout_s
        )
    stats1 = server.stats()
    served = stats1["served"] - stats0["served"]
    padded = stats1["padded_rows"] - stats0["padded_rows"]
    fill = served / (served + padded) if served + padded else 0.0
    row = {
        "kind": "serve_bench",
        "ts": time.time(),
        "mode": mode,
        "requests": len(lat_ms),
        "rejected": rejected,
        "offered_rps": round(rps, 1) if mode == "open" else None,
        "images_per_sec": round(len(lat_ms) / wall, 1) if wall > 0 else 0.0,
        "mean_fill_ratio": round(fill, 4),
        "compiles_after_warmup": stats1["compiles_after_warmup"],
        **_percentiles(lat_ms),
    }
    # Zero-copy assertion (ISSUE 16): input bytes touched exactly once
    # between the transport and device_put — the ledger-checked number.
    copies = _sum_host_stat(stats1, "input_copies") - _sum_host_stat(
        stats0, "input_copies"
    )
    if served > 0 and copies > 0:
        row["copies_per_request"] = round(copies / served, 6)
    hedges1 = stats1.get("router", {}).get("hedges")
    if hedges1 is not None:
        row["hedged"] = hedges1 - (stats0.get("router", {}).get("hedges") or 0)
    if fleet_hosts:
        row["fleet_hosts"] = fleet_hosts
        row["per_host"] = _per_host_breakdown(
            snaps0, server.host_snapshots(), stats0, stats1
        )
    return row


def run_point_replay(server, pool, workload, *, timeout_s, fleet_hosts=0,
                     use_models=False):
    """One trace-replay sweep point (ISSUE 18): re-drive the workload's
    RECORDED arrival process against the candidate server. Latency is
    measured from each intended arrival instant (open_loop semantics).
    Admission rejections are SHED, never deferred — a deferral would
    distort the recorded arrival process the row claims to have replayed,
    so the reject count is the candidate config's honest admission answer
    to this exact load shape."""
    from mpi_pytorch_tpu.obs.replay import replay_workload

    stats0 = server.stats()
    snaps0 = server.host_snapshots() if fleet_hosts else None

    def submit(i, req):
        if use_models and req.model is not None:
            return server.submit(pool[i % len(pool)], model=req.model)
        return server.submit(pool[i % len(pool)])

    res = replay_workload(submit, workload, timeout_s=timeout_s)
    stats1 = server.stats()
    served = stats1["served"] - stats0["served"]
    padded = stats1["padded_rows"] - stats0["padded_rows"]
    fill = served / (served + padded) if served + padded else 0.0
    if res["failed"]:
        print(f"WARNING: {res['failed']} replayed request(s) FAILED "
              "(not admission rejects) — the row undercounts them",
              file=sys.stderr)
    row = {
        "kind": "serve_bench",
        "ts": time.time(),
        "mode": "replay",
        "requests": res["accepted"],
        "rejected": res["rejected"],
        "offered_rps": workload.offered_rps,
        "images_per_sec": res["images_per_sec"],
        "mean_fill_ratio": round(fill, 4),
        "compiles_after_warmup": stats1["compiles_after_warmup"],
        **_percentiles(res["lat_ms"]),
    }
    copies = _sum_host_stat(stats1, "input_copies") - _sum_host_stat(
        stats0, "input_copies"
    )
    if served > 0 and copies > 0:
        row["copies_per_request"] = round(copies / served, 6)
    hedges1 = stats1.get("router", {}).get("hedges")
    if hedges1 is not None:
        row["hedged"] = hedges1 - (stats0.get("router", {}).get("hedges") or 0)
    if fleet_hosts:
        row["fleet_hosts"] = fleet_hosts
        row["per_host"] = _per_host_breakdown(
            snaps0, server.host_snapshots(), stats0, stats1
        )
    return row


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--num-classes", type=int, default=64500)
    ap.add_argument("--topk", type=int, default=5)
    ap.add_argument("--compute-dtype", default="bfloat16")
    ap.add_argument("--bucket-sets", default="1,8,32,128;1,32,512",
                    help="semicolon-separated bucket SETS; one server build "
                    "(and one warmup compile set) per entry")
    ap.add_argument("--max-wait-ms", default="5",
                    help="comma list; swept live per server (no recompile)")
    ap.add_argument("--requests", type=int, default=2000)
    ap.add_argument("--concurrency", type=int, default=32,
                    help="closed-loop client threads")
    ap.add_argument("--rps", default="0",
                    help="comma list of offered open-loop rates; 0 = closed "
                    "loop only for that sweep point")
    ap.add_argument("--queue-depth", type=int, default=1024)
    ap.add_argument("--fleet", type=int, default=0,
                    help="N > 0: drive a local N-host fleet (threads, one "
                    "shared executable set) through the load-aware router "
                    "instead of a single server; rows gain fleet_hosts + "
                    "the per_host fill/latency breakdown")
    ap.add_argument("--transport", default="local",
                    choices=("local", "remote", "framed"),
                    help="remote (needs --fleet N): each host is a REAL "
                    "python -m mpi_pytorch_tpu.serve.host subprocess and "
                    "requests cross the wire (serve/fleet/remote.py); rows "
                    "gain transport='http' so check_regression never "
                    "compares them against in-process baselines. framed "
                    "(ISSUE 16): same subprocess fleet, but the data plane "
                    "is the binary framed wire (serve/wire.py — persistent "
                    "pooled connections, pipelining, CANCEL); rows stamp "
                    "transport='framed' (its own trend line)")
    ap.add_argument("--hedge", action="store_true",
                    help="with --transport framed and --fleet >= 2: hedge "
                    "tail requests to the second-best host after a per-host "
                    "p99-derived deadline, first completion wins, loser "
                    "CANCELled over the wire; rows stamp "
                    "transport='framed+hedge' and the hedged count")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout-s", type=float, default=120.0)
    ap.add_argument("--fused-head", action="store_true",
                    help="serve through ops.fused_head_ce.head_predict "
                    "(TPU; forces topk=1)")
    ap.add_argument("--precision", default="bf16",
                    help="comma list over {bf16,int8}; both values build "
                    "ONE server holding both startup-compiled executable "
                    "sets and sweep by switching live (no recompile); "
                    "int8 rows carry the startup parity_top1 stamp")
    ap.add_argument("--models", default="",
                    help="comma list of tenant specs (ISSUE 14, e.g. "
                    "'resnet18,mobilenet_v2'): ONE multi-tenant server/"
                    "fleet serves the whole zoo, sweeps drive interleaved "
                    "per-tenant traffic, and each sweep point yields one "
                    "row PER TENANT (model-keyed p99/fill/rejected "
                    "columns; check_regression keys model + load_shape "
                    "into the trend-line identity)")
    ap.add_argument("--hot-model", default="",
                    help="with --models: skew the offered load onto this "
                    "tenant (80%% hot / 20%% split over the rest) — the "
                    "hot-tenant starvation shape; rows stamp "
                    "load_shape='hot:<model>'")
    ap.add_argument("--pack-budget-mb", type=float, default=0.0,
                    help="with --models: the per-host packing budget "
                    "(serve_pack_budget_mb; 0 = unbounded)")
    ap.add_argument("--trace-sample-rate", type=float, default=0.0,
                    help="> 0 (needs --fleet N): distributed tracing at "
                    "the router front door + the FleetCollector, and each "
                    "row gains per_phase — the collector-derived "
                    "queue/preprocess/device/wire p50/p99 breakdown for "
                    "that sweep point (ISSUE 13)")
    ap.add_argument("--replay", default="",
                    help="path to a fleet-trace JSONL (or a saved workload "
                    "artifact) to REPLAY (ISSUE 18): re-drive the recorded "
                    "arrival process — not Poisson — against the candidate "
                    "config over either transport. --rps is ignored; each "
                    "(bucket set, precision, wait) point yields one "
                    "mode='replay' row stamped with the workload "
                    "fingerprint and the recorded-vs-replayed differential "
                    "report (replay_diff)")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="with --replay: time-warp factor (2.0 = replay "
                    "twice as fast). Warping changes the workload "
                    "fingerprint — a warped replay is its own trend line; "
                    "rows also stamp speed")
    ap.add_argument("--replay-window", default="",
                    help="with --replay: 'START,END' arrival-offset window "
                    "in seconds — trim the workload to arrivals in "
                    "[START, END) before replaying")
    ap.add_argument("--fleet-trace-file", default="",
                    help="with --fleet N and --trace-sample-rate > 0: write "
                    "the kept traces to this JSONL — the RECORD half of "
                    "the record-and-replay recipe (record at sample rate "
                    "1.0 for an exact workload)")
    ap.add_argument("--canary-probes", type=int, default=0,
                    help="arm the golden-set quality canary (ISSUE 19): N "
                         "shadow probes per tenant per swept point through "
                         "the fleet front door; rows gain agreement_top1 "
                         "(needs a local --fleet N)")
    ap.add_argument("--drift-window", type=int, default=0,
                    help="arm prediction-drift detection: per-tenant top-1 "
                         "histograms over windows of N real requests "
                         "(needs a local --fleet N)")
    ap.add_argument("--serve-shard-degree", type=int, default=1,
                    help="> 1: single-model MODEL-parallel serving — "
                    "params fsdp:K-sharded over the model axis of a "
                    "nested (data, model) serve mesh (ISSUE 17); rows "
                    "gain shard_degree and key a separate trend line")
    ap.add_argument("--serve-pipe-stages", type=int, default=1,
                    help="> 1: single-model PIPELINE-parallel serving — "
                    "the model stage-split over K chip groups of a nested "
                    "(data, pipe) serve mesh, flushes streamed through as "
                    "micro-batches (ISSUE 20); rows gain pipe_stages + "
                    "bubble_frac and key a separate trend line")
    ap.add_argument("--out", default="",
                    help="also write rows to this JSONL file (overwritten)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU tier-1 mode: tiny model, two bucket sets, "
                    "closed+open loop, seconds not minutes")
    args = ap.parse_args()

    if args.smoke:
        args.model, args.image, args.num_classes = "resnet18", 32, 64
        args.topk, args.compute_dtype = 3, "float32"
        # Fleet smoke: one bucket set (the hosts share its executables,
        # but each SET is a fresh fleet build — keep tier-1 cheap).
        args.bucket_sets = "1,4" if (args.fleet or args.models) else "1,4;1,8"
        args.max_wait_ms, args.requests, args.concurrency = "2", 48, 8
        args.rps = "0,400"

    # Pin the platform IN-SCRIPT: this image's sitecustomize registers the
    # TPU plugin at interpreter startup, so the env var alone loses (the
    # parse_config trick, config.py) — and --smoke is DEFINED as the CPU
    # mode, so it must never claim the TPU grant.
    platform = (
        os.environ.get("MPT_PLATFORM")
        or os.environ.get("JAX_PLATFORMS")
        or ("cpu" if args.smoke else "")
    )
    import jax

    if platform:
        jax.config.update("jax_platforms", platform.split(",")[0].strip())

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve import FleetServer, InferenceServer, RemoteFleet

    if args.transport in ("remote", "framed") and args.fleet <= 0:
        print(f"--transport {args.transport} needs --fleet N (N >= 1)",
              file=sys.stderr)
        return 2
    if args.hedge and (args.transport != "framed" or args.fleet < 2):
        print("--hedge needs --transport framed and --fleet >= 2 (a hedge "
              "needs a second host and a CANCEL-capable wire)",
              file=sys.stderr)
        return 2
    if args.trace_sample_rate > 0 and args.fleet <= 0:
        # The trace id is minted at the FRONT DOOR, which is the fleet
        # router — a single bare server has no front door to mint at.
        print("--trace-sample-rate needs --fleet N (the router is the "
              "minting front door)", file=sys.stderr)
        return 2
    if args.serve_shard_degree > 1 and (args.fleet > 0 or args.models):
        # The single-model knob: a fleet's hosts each own the full mesh,
        # and zoo tenants pick residency per-spec (shard=K) or from the
        # packing planner instead.
        print("--serve-shard-degree needs a bare single-model server "
              "(no --fleet/--models)", file=sys.stderr)
        return 2
    if args.serve_pipe_stages > 1 and (
            args.fleet > 0 or args.models or args.serve_shard_degree > 1):
        # Same single-model scoping as the shard knob, and pipe/fsdp are
        # rival layouts of the same chips (config.validate_config agrees).
        print("--serve-pipe-stages needs a bare single-model server "
              "(no --fleet/--models/--serve-shard-degree)", file=sys.stderr)
        return 2
    if (args.canary_probes or args.drift_window) and (
            args.fleet <= 0 or args.transport != "local"):
        # The gate/prober live in FleetServer; remote hosts are separate
        # processes whose fleet object is theirs, not ours.
        print("--canary-probes/--drift-window need a local --fleet N "
              "(the canary gate and prober are FleetServer wiring)",
              file=sys.stderr)
        return 2
    cache_dir = ""
    if args.transport in ("remote", "framed"):
        # Remote hosts are fresh processes: a shared persistent
        # compilation cache is what keeps an N-host build at ~one compile
        # set (the warm-start recipe, docs/SERVING.md "Remote fleet").
        import tempfile

        cache_dir = tempfile.mkdtemp(prefix="mpt_bench_remote_cache_")

    workload = None
    if args.replay:
        from mpi_pytorch_tpu.obs.replay import WorkloadError, load_workload

        try:
            workload = load_workload(args.replay)
            if args.replay_window:
                try:
                    start_s, end_s = (
                        float(x) for x in args.replay_window.split(","))
                except ValueError:
                    print("--replay-window wants 'START,END' seconds",
                          file=sys.stderr)
                    return 2
                workload = workload.trim(start_s, end_s)
            if args.speed != 1.0:
                # Warp HERE so the fingerprint stamped on rows identifies
                # the arrival process actually replayed.
                workload = workload.warp(args.speed)
        except (OSError, WorkloadError) as e:
            print(f"--replay: {e}", file=sys.stderr)
            return 2
        if workload.defaults_applied:
            print(f"note: {workload.defaults_applied} recorded request(s) "
                  "predate schema v14 root attrs — replayed with documented "
                  "defaults (model=None, rows=1)", file=sys.stderr)
        print(f"replaying workload {workload.fingerprint}: "
              f"{len(workload.requests)} arrivals over "
              f"{workload.duration_s:.2f}s ({workload.offered_rps} rps)",
              file=sys.stderr)

    out_rows = []
    pool = _image_pool(32, (args.image, args.image), args.seed)
    waits = [float(w) for w in args.max_wait_ms.split(",") if w.strip()]
    rates = [float(r) for r in args.rps.split(",") if r.strip()]
    if workload is not None:
        rates = [0.0]  # one replay point per (set, precision, wait)
    precisions = [p.strip() for p in args.precision.split(",") if p.strip()]
    bad_prec = sorted(set(precisions) - {"bf16", "int8"})
    if not precisions or bad_prec:
        print(f"unknown --precision value(s): {bad_prec}", file=sys.stderr)
        return 2
    # Any int8 point needs the bf16 set too — it is the parity REFERENCE:
    # an int8 row without its parity_top1 stamp is half a row (the v7
    # schema contract), so an int8-only sweep still builds both sets and
    # just doesn't drive the bf16 one. A bf16-only sweep builds one set.
    serve_precision = "both" if "int8" in precisions else "bf16"
    # Stamp rows only when the precision axis is LIVE (non-bf16 involved):
    # a default pure-bf16 run keeps v6-identical rows, so its trend lines
    # keep pairing with pre-v7 baselines (the serve-record rule).
    stamp_precision = "int8" in precisions
    tenant_models: list[str] = []
    tenant_weights: list[float] = []
    load_shape = "uniform"
    if args.models:
        from mpi_pytorch_tpu.serve.zoo import parse_model_specs

        tenant_models = [s.model for s in parse_model_specs(args.models)]
        if args.hot_model:
            if args.hot_model not in tenant_models:
                print(f"--hot-model {args.hot_model!r} is not in --models",
                      file=sys.stderr)
                return 2
            if len(tenant_models) < 2:
                print("--hot-model needs >= 2 tenants", file=sys.stderr)
                return 2
            # The hot-tenant skewed shape: 80% of offered load on the
            # hot tenant, the rest split evenly — the starvation drill.
            cold_share = 0.2 / (len(tenant_models) - 1)
            tenant_weights = [
                0.8 if m == args.hot_model else cold_share
                for m in tenant_models
            ]
            load_shape = f"hot:{args.hot_model}"
        else:
            tenant_weights = [1.0 / len(tenant_models)] * len(tenant_models)
    elif args.hot_model or args.pack_budget_mb:
        print("--hot-model/--pack-budget-mb need --models", file=sys.stderr)
        return 2

    for bucket_set in [b for b in args.bucket_sets.split(";") if b.strip()]:
        cfg = Config(
            model_name=args.model, num_classes=args.num_classes,
            width=args.image, height=args.image, synthetic_data=True,
            compute_dtype=args.compute_dtype, serve_buckets=bucket_set,
            serve_max_wait_ms=waits[0], serve_queue_depth=args.queue_depth,
            serve_topk=args.topk, fused_head_eval=args.fused_head,
            serve_fleet_hosts=max(0, args.fleet),
            serve_precision=serve_precision,
            serve_models=args.models,
            serve_pack_budget_mb=args.pack_budget_mb,
            serve_shard_degree=max(1, args.serve_shard_degree),
            serve_pipe_stages=max(1, args.serve_pipe_stages),
            serve_transport="framed" if args.transport == "framed"
            else "http",
            serve_hedge=args.hedge,
            compilation_cache_dir=cache_dir,
            trace_sample_rate=args.trace_sample_rate,
            fleet_trace_file=args.fleet_trace_file,
            # The collector is what derives the per-phase breakdown; a
            # tight scrape keeps the sweep point's spans inside the point.
            serve_collect_interval_s=0.1 if args.trace_sample_rate > 0
            else 0.0,
            serve_canary_probes=max(0, args.canary_probes),
            serve_drift_window=max(0, args.drift_window),
            metrics_file="", log_file="", eval_log_file="",
        )
        cfg.validate_config()
        if args.transport in ("remote", "framed"):
            server = RemoteFleet(cfg)
        elif args.fleet > 0:
            server = FleetServer(cfg, load_checkpoint=False)
        elif args.models:
            from mpi_pytorch_tpu.serve.zoo import ZooServer

            server = ZooServer(cfg, load_checkpoint=False)
        else:
            server = InferenceServer(cfg, load_checkpoint=False)
        if args.canary_probes and getattr(server, "prober", None) is not None:
            # Pin the healthy references BEFORE the sweep, with the
            # quality-fault gate disarmed: the bench's references are
            # ground truth by construction, so a drill fault (the
            # logit-noise gate pair below) must surface as sweep-row
            # disagreement — never silently poison the baseline the
            # sweep is scored against.
            _noise_gates = {
                k: os.environ.pop(k)
                for k in ("MPT_FAULT_LOGIT_NOISE_PCT",
                          "MPT_FAULT_LOGIT_NOISE_MODEL")
                if k in os.environ
            }
            try:
                server.prober.probe_once()
            finally:
                os.environ.update(_noise_gates)
        try:
            for precision in precisions:
                if server.precision != precision:
                    server.set_precision(precision)
                for wait_ms in waits:
                    server.set_max_wait_ms(wait_ms)
                    for rps in rates:
                        mode = "open" if rps > 0 else "closed"
                        if workload is not None:
                            row = run_point_replay(
                                server, pool, workload,
                                timeout_s=args.timeout_s,
                                fleet_hosts=max(0, args.fleet),
                                use_models=bool(tenant_models),
                            )
                            if not tenant_models:
                                row["model"] = args.model
                            rows = [row]
                        elif tenant_models:
                            rows = run_point_tenants(
                                server, pool, tenant_models, tenant_weights,
                                mode=mode, requests=args.requests,
                                concurrency=args.concurrency, rps=rps,
                                seed=args.seed, timeout_s=args.timeout_s,
                                fleet_hosts=max(0, args.fleet),
                                load_shape=load_shape,
                            )
                        else:
                            row = run_point(
                                server, pool, mode=mode,
                                requests=args.requests,
                                concurrency=args.concurrency, rps=rps,
                                seed=args.seed, timeout_s=args.timeout_s,
                                fleet_hosts=max(0, args.fleet),
                            )
                            row["model"] = args.model
                            rows = [row]
                        canary_scores = None
                        if (args.canary_probes
                                and getattr(server, "prober", None)
                                is not None):
                            # One probe cycle per swept point: the row's
                            # quality stamp measures THIS point's config
                            # (precision/wait/buckets), not a stale one.
                            canary_scores = server.prober.probe_once()
                        collector = getattr(server, "collector", None)
                        per_phase = None
                        if collector is not None:
                            # One forced scrape so the point's spans are
                            # all in, then the per-phase p50/p99 deltas
                            # since the previous point (ISSUE 13
                            # satellite: the attribution columns).
                            collector.tick()
                            per_phase = collector.drain_phase_stats()
                        for row in rows:
                            row.update(
                                buckets=bucket_set, max_wait_ms=wait_ms,
                                chips=jax.device_count(),
                            )
                            if args.transport == "remote":
                                row["transport"] = "http"
                            elif args.transport == "framed":
                                row["transport"] = (
                                    "framed+hedge" if args.hedge
                                    else "framed"
                                )
                            if per_phase and not tenant_models:
                                # Per-phase spans are not tenant-split:
                                # attach only to single-model rows.
                                row["per_phase"] = per_phase
                            if workload is not None:
                                from mpi_pytorch_tpu.obs.replay import (
                                    differential_report,
                                    render_diff,
                                )

                                row["workload"] = workload.fingerprint
                                if args.speed != 1.0:
                                    row["speed"] = args.speed
                                diff = differential_report(
                                    workload,
                                    {"submitted": (row["requests"]
                                                   + row["rejected"]),
                                     "rejected": row["rejected"],
                                     "images_per_sec":
                                         row["images_per_sec"]},
                                    per_phase,
                                )
                                row["replay_diff"] = diff
                                for line in render_diff(diff):
                                    print(line, file=sys.stderr)
                            if args.serve_shard_degree > 1:
                                # Schema-v13: the model-parallel axis is
                                # its own trend-line identity — a sharded
                                # row must never pair with a replicated
                                # baseline.
                                row["shard_degree"] = args.serve_shard_degree
                            if args.serve_pipe_stages > 1:
                                # Schema-v16: the pipeline axis — its own
                                # trend line, with the last flush's
                                # measured fill/drain bubble as evidence.
                                row["pipe_stages"] = args.serve_pipe_stages
                                exe = getattr(server, "_exe", None)
                                lf = (
                                    exe.last_flush()
                                    if hasattr(exe, "last_flush") else None
                                )
                                if lf:
                                    row["bubble_frac"] = round(
                                        float(lf["bubble_frac"]), 4
                                    )
                            if stamp_precision:
                                row["precision"] = precision
                            if (precision == "int8"
                                    and server.parity_top1 is not None):
                                row["parity_top1"] = server.parity_top1
                            if canary_scores:
                                # Schema-v15 quality axis: the canary's
                                # live top-1 agreement for this row's
                                # tenant (check_regression fails a >2-pt
                                # absolute drop vs baseline).
                                sc = canary_scores.get(
                                    row.get("model") or ""
                                )
                                if sc and "agreement_top1" in sc:
                                    row["agreement_top1"] = (
                                        sc["agreement_top1"]
                                    )
                            print(json.dumps(row), flush=True)
                            out_rows.append(row)
        finally:
            server.close()

    bad = [r for r in out_rows if r["compiles_after_warmup"] != 0]
    if bad:
        print(
            f"WARNING: {len(bad)} row(s) observed steady-state compiles — "
            "the zero-compile invariant is broken; rows are tainted",
            file=sys.stderr,
        )
    if args.out:
        with open(args.out, "w") as f:
            for row in out_rows:
                f.write(json.dumps(row) + "\n")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
