"""max_pool_argmax ≡ nn.max_pool — values AND gradients, every zoo config.

The index-based pool (ops/pooling.py) replaces XLA's select-and-scatter
backward; these tests pin exact equivalence on the pool configs the model
zoo actually uses, including tie-heavy integer-valued inputs where the
first-match tie-break rule is load-bearing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_pytorch_tpu.models.common import max_pool_xla
from mpi_pytorch_tpu.ops.pooling import max_pool_argmax

# (window, stride, padding) as used by the zoo:
# resnet/densenet stems (3,2,1); alexnet/squeezenet/inception (3,2,VALID);
# vgg (2,2,VALID).
ZOO_CONFIGS = [(3, 2, 1), (3, 2, "VALID"), (2, 2, "VALID")]


def _pad(padding):
    if isinstance(padding, int):
        return ((padding, padding), (padding, padding))
    return ((0, 0), (0, 0))


def max_pool(x, window, stride, padding):
    return max_pool_argmax(x, (window, window), (stride, stride), _pad(padding))


def _rand(shape, seed, tie_heavy=False, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    if tie_heavy:
        # Small-integer values + zeros: many exact window ties, and
        # all-zero windows (the post-relu case where the relu mask matters).
        x = rng.integers(-2, 3, size=shape).astype(np.float32)
    else:
        x = rng.standard_normal(shape).astype(np.float32)
    return jnp.asarray(x, dtype)


@pytest.mark.parametrize("window,stride,padding", ZOO_CONFIGS)
@pytest.mark.parametrize("tie_heavy", [False, True])
def test_values_and_grads_match_xla(window, stride, padding, tie_heavy):
    x = _rand((2, 13, 13, 8), seed=window * 10 + stride, tie_heavy=tie_heavy)

    def f_new(x):
        return jnp.sum(max_pool(x, window, stride, padding) ** 2)

    def f_ref(x):
        return jnp.sum(max_pool_xla(x, window, stride, padding) ** 2)

    v_new, g_new = jax.value_and_grad(f_new)(x)
    v_ref, g_ref = jax.value_and_grad(f_ref)(x)
    np.testing.assert_array_equal(np.asarray(v_new), np.asarray(v_ref))
    np.testing.assert_array_equal(np.asarray(g_new), np.asarray(g_ref))


def test_values_match_bf16():
    x = _rand((2, 16, 16, 8), seed=0, dtype=jnp.bfloat16)
    got = max_pool(x, 3, 2, 1)
    want = max_pool_xla(x, 3, 2, 1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_grad_ties_first_match():
    """A window of identical values routes the whole gradient to the FIRST
    element (select-and-scatter's ge-fold semantics)."""
    x = jnp.ones((1, 2, 2, 1), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(max_pool(x, 2, 2, "VALID")))(x)
    want = np.zeros((1, 2, 2, 1), np.float32)
    want[0, 0, 0, 0] = 1.0
    np.testing.assert_array_equal(np.asarray(g), want)


def test_grad_under_jit_and_odd_sizes():
    """299px-style odd spatial dims (inception) under jit."""
    x = _rand((2, 17, 17, 4), seed=3)

    @jax.jit
    def g_new(x):
        return jax.grad(lambda x: jnp.sum(max_pool(x, 3, 2, "VALID") * 3.0))(x)

    @jax.jit
    def g_ref(x):
        return jax.grad(lambda x: jnp.sum(max_pool_xla(x, 3, 2, "VALID") * 3.0))(x)

    np.testing.assert_array_equal(np.asarray(g_new(x)), np.asarray(g_ref(x)))


def test_eval_path_has_no_index_output():
    """The primal (non-differentiated) path computes only the max — the
    jaxpr must contain no uint8 argmax bookkeeping."""
    x = _rand((1, 8, 8, 4), seed=5)
    jaxpr = jax.make_jaxpr(
        lambda x: max_pool_argmax(x, (3, 3), (2, 2), ((1, 1), (1, 1)))
    )(x)
    assert "u8" not in str(jaxpr)


def test_vmap_compat():
    x = _rand((3, 2, 12, 12, 4), seed=6)
    got = jax.vmap(lambda x: max_pool(x, 3, 2, 1))(x)
    want = jax.vmap(lambda x: max_pool_xla(x, 3, 2, 1))(x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
