"""Anomaly flight recorder (obs/flight.py): ring capacity, the
MetricsWriter tap, auto-dump on fault/alert records, dump-file contents
(schema-clean, atomic), the dump cap, and the trainer failure path."""

import json
import os

from mpi_pytorch_tpu.obs.flight import FlightRecorder
from mpi_pytorch_tpu.obs.schema import validate_record
from mpi_pytorch_tpu.utils.logging import MetricsWriter


def _step(i):
    return {"kind": "step", "epoch": 0, "step": i, "loss": 1.0}


def test_ring_bounded_and_dump_carries_last_n(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=8)
    for i in range(50):
        fr.record({"ts": float(i), **_step(i)})
    path = fr.dump("manual")
    data = json.load(open(path))
    assert data["reason"] == "manual" and data["process"] == 0
    steps = [r["step"] for r in data["records"]]
    assert steps == list(range(42, 50))  # exactly the last 8
    for rec in data["records"]:
        assert validate_record(rec) == []


def test_tap_forwards_and_auto_dumps_on_fault_and_alert(tmp_path):
    inner = MetricsWriter(str(tmp_path / "m.jsonl"))
    fr = FlightRecorder(str(tmp_path / "flight"), capacity=16)
    writer = fr.tap(inner)
    writer.write(_step(0))
    writer.write({"kind": "fault", "reason": "injected_kill"})
    writer.write(_step(1))
    writer.write(
        {"kind": "alert", "rule": "p99", "severity": "warn"}
    )
    writer.close()

    # The stream still got every record, ts-stamped once.
    lines = [json.loads(line) for line in open(tmp_path / "m.jsonl")]
    assert [r["kind"] for r in lines] == ["step", "fault", "step", "alert"]
    assert all("ts" in r for r in lines)

    dumps = sorted(os.listdir(tmp_path / "flight"))
    assert len(dumps) == 2
    assert "fault_injected_kill" in dumps[0] and dumps[0].endswith(".p0.json")
    assert "alert_p99" in dumps[1]
    fault_dump = json.load(open(tmp_path / "flight" / dumps[0]))
    # The dump ends with its own trigger, preceded by the context records.
    assert [r["kind"] for r in fault_dump["records"]] == ["step", "fault"]
    alert_dump = json.load(open(tmp_path / "flight" / dumps[1]))
    assert [r["kind"] for r in alert_dump["records"]] == [
        "step", "fault", "step", "alert",
    ]


def test_dump_cap_stops_disk_spam(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=4, max_dumps=3)
    for i in range(10):
        fr.record({"ts": float(i), "kind": "fault", "reason": f"f{i}"})
    assert len(os.listdir(tmp_path)) == 3
    assert fr.dump("manual") is None  # cap reached: refused, not raised


def test_closed_recorder_refuses_dumps_keeps_ring(tmp_path):
    fr = FlightRecorder(str(tmp_path), capacity=4)
    fr.record({"ts": 0.0, **_step(0)})
    fr.close()
    fr.close()  # idempotent
    assert fr.dump("late") is None
    assert list(fr._ring)  # evidence still inspectable in-process


def test_no_stray_tmp_files_after_dump(tmp_path):
    """Dumps are atomic (tmp+rename): a reader never sees a half-written
    evidence file, and no .tmp litter survives."""
    fr = FlightRecorder(str(tmp_path), capacity=4)
    fr.record({"ts": 0.0, "kind": "fault", "reason": "x"})
    names = os.listdir(tmp_path)
    assert names and not [n for n in names if n.endswith(".tmp")]


def test_trainer_crash_path_dumps_flight(tmp_path):
    """A NaN'd run (the sentinel abort) must leave a crash dump next to
    the flushed trace — the failure-path discipline the tracer already
    follows, extended to the flight recorder."""
    import pytest

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.obs import NonFiniteLossError
    from mpi_pytorch_tpu.train.trainer import train

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cfg = Config()
    cfg.debug = True
    cfg.debug_sample_size = 48
    cfg.train_csv = os.path.join(REPO, "data", "train_sample.csv")
    cfg.test_csv = os.path.join(REPO, "data", "test_sample.csv")
    cfg.synthetic_data = True
    cfg.num_classes = 64
    cfg.batch_size = 16
    cfg.width = cfg.height = 16
    cfg.num_epochs = 2
    cfg.compute_dtype = "float32"
    cfg.learning_rate = 1e38  # NaNs within two steps
    cfg.validate = False
    cfg.loader_workers = 2
    cfg.log_every_steps = 0
    cfg.step_metrics = True
    cfg.checkpoint_dir = str(tmp_path / "ckpt")
    cfg.log_file = str(tmp_path / "training.log")
    cfg.metrics_file = str(tmp_path / "metrics.jsonl")
    cfg.flight_dir = str(tmp_path / "flight")
    cfg.validate_config()
    with pytest.raises(NonFiniteLossError):
        train(cfg)

    dumps = sorted(os.listdir(cfg.flight_dir))
    # The anomaly record is not an auto-dump kind, so the evidence comes
    # from the failure path's explicit crash dump.
    assert any("crash" in d for d in dumps), dumps
    crash = json.load(
        open(os.path.join(cfg.flight_dir, [d for d in dumps if "crash" in d][0]))
    )
    kinds = [r["kind"] for r in crash["records"]]
    assert "anomaly" in kinds and "step" in kinds
    for rec in crash["records"]:
        assert validate_record(rec) == []
