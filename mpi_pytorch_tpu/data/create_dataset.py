"""Offline dataset builder — capability parity with the reference's
``create_dataset.py`` (component #8 in SURVEY §2a).

The reference reads the Herbarium ``metadata.json``, joins its ``images`` ×
``annotations`` tables into one dataframe (``create_dataset.py:34-39``),
samples ``N_IMAGES`` rows with seed 0 (``:52``), splits 80/20 (``:55``),
writes ``data/{train,test}_sample.csv`` (``:56-57``) and copies the image
files into ``data/img/{train,test}`` (``:62-66``). This builder does the
same, plus a ``--synthetic`` mode that *generates* a labeled JPEG dataset
(class-conditioned patterns) so the full decode→train path can run in
environments where the Herbarium images are unavailable (they are gitignored
in the reference too).

    python -m mpi_pytorch_tpu.data.create_dataset \
        --metadata train/metadata.json --img-root train/ --out data/

    python -m mpi_pytorch_tpu.data.create_dataset \
        --synthetic 1000 --num-classes 50 --out data/
"""

from __future__ import annotations

import argparse
import json
import os
import shutil

import numpy as np
import pandas as pd

CSV_COLUMNS = ["file_name", "height", "id", "license", "width", "category_id"]


def read_metadata(path: str) -> pd.DataFrame:
    """images × annotations join on image id (≙ ``create_dataset.py:34-39``)."""
    with open(path) as f:
        meta = json.load(f)
    images = pd.DataFrame(meta["images"])
    # annotations carry their own "id"; drop it so the image id survives the
    # merge un-suffixed (the reference CSVs' "id" column is the image id).
    annotations = pd.DataFrame(meta["annotations"]).drop(columns=["id"], errors="ignore")
    df = images.merge(annotations, left_on="id", right_on="image_id", how="inner")
    keep = [c for c in CSV_COLUMNS if c in df.columns]
    return df[keep]


def sample_and_split(
    df: pd.DataFrame, n_images: int, seed: int = 0, train_frac: float = 0.8
) -> tuple[pd.DataFrame, pd.DataFrame]:
    """Seed-0 sample + 80/20 split (≙ ``create_dataset.py:52-55``)."""
    df = df.sample(n=min(n_images, len(df)), random_state=seed)
    n_train = int(len(df) * train_frac)
    return df.iloc[:n_train].reset_index(drop=True), df.iloc[n_train:].reset_index(drop=True)


def write_split(
    train_df: pd.DataFrame,
    test_df: pd.DataFrame,
    out_dir: str,
    img_root: str | None = None,
    copy_images: bool = True,
) -> tuple[str, str]:
    """Write the two manifests; optionally copy images into ``out/img/...``
    (≙ ``create_dataset.py:56-66``)."""
    os.makedirs(out_dir, exist_ok=True)
    train_csv = os.path.join(out_dir, "train_sample.csv")
    test_csv = os.path.join(out_dir, "test_sample.csv")
    train_df.to_csv(train_csv)
    test_df.to_csv(test_csv)
    if img_root and copy_images:
        for split, df in (("train", train_df), ("test", test_df)):
            for fname in df["file_name"]:
                # Preserve the nested file_name path — the manifests keep it,
                # and the loader joins img_dir with it (data/pipeline.py).
                dst = os.path.join(out_dir, "img", split, fname)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                if not os.path.exists(dst):
                    shutil.copyfile(os.path.join(img_root, fname), dst)
    return train_csv, test_csv


def generate_synthetic(
    out_dir: str, n_images: int, num_classes: int, image_size: int = 128, seed: int = 0
) -> pd.DataFrame:
    """Generate a labeled JPEG dataset with the same class-conditioned
    patterns the in-memory synthetic loader uses (data/pipeline.py), so
    on-disk decode runs produce learnable data too."""
    from PIL import Image

    from mpi_pytorch_tpu.data.pipeline import synthetic_image

    rng = np.random.default_rng(seed)
    rows = []
    for split in ("train", "test"):
        os.makedirs(os.path.join(out_dir, "img", split), exist_ok=True)
    labels = rng.integers(0, num_classes, size=n_images)
    for i, label in enumerate(labels):
        split = "train" if i < int(n_images * 0.8) else "test"
        fname = f"synthetic_{i:06d}.jpg"
        img = (synthetic_image(int(label), (image_size, image_size)) * 255).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(out_dir, "img", split, fname), quality=90)
        rows.append(
            {"file_name": fname, "height": image_size, "id": i, "license": 0,
             "width": image_size, "category_id": int(label), "split": split}
        )
    return pd.DataFrame(rows)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--metadata", help="Herbarium metadata.json path")
    ap.add_argument("--img-root", help="root directory the metadata file_names are relative to")
    ap.add_argument("--out", default="data")
    ap.add_argument("--n-images", type=int, default=50000)  # utils.py:14
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--no-copy", action="store_true", help="write CSVs only")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="generate N synthetic labeled JPEGs instead of reading metadata")
    ap.add_argument("--num-classes", type=int, default=100, help="synthetic mode classes")
    ap.add_argument("--image-size", type=int, default=128, help="synthetic mode size")
    args = ap.parse_args(argv)

    if args.synthetic:
        df = generate_synthetic(args.out, args.synthetic, args.num_classes,
                                args.image_size, args.seed)
        train_df = df[df["split"] == "train"].drop(columns="split").reset_index(drop=True)
        test_df = df[df["split"] == "test"].drop(columns="split").reset_index(drop=True)
        train_csv, test_csv = write_split(train_df, test_df, args.out, copy_images=False)
    else:
        if not args.metadata:
            raise SystemExit("--metadata (or --synthetic N) is required")
        if not args.img_root and not args.no_copy:
            raise SystemExit("--img-root is required to copy images (or pass --no-copy)")
        df = read_metadata(args.metadata)
        train_df, test_df = sample_and_split(df, args.n_images, args.seed)
        train_csv, test_csv = write_split(
            train_df, test_df, args.out, args.img_root, copy_images=not args.no_copy
        )
    print(f"wrote {train_csv} ({len(train_df)} rows), {test_csv} ({len(test_df)} rows)")


if __name__ == "__main__":
    main()
