"""Training driver — the TPU-native ``main.py``.

Structural parity with the reference driver (``main.py:49-189``), stage by
stage:

| reference (main.py)                         | here                           |
|---------------------------------------------|--------------------------------|
| MPI world setup (``:16-18``)                | mesh over all chips            |
| rank-0 CSV read + scatter (``:73-91``)      | ``load_manifests`` + per-host shard |
| DataLoader(batch, shuffle) (``:99-102``)    | ``DataLoader`` (prefetching)   |
| model/opt init (``:121-125``)               | ``create_model_bundle`` + optax|
| FROM_CHECKPOINT resume (``:127-129``)       | ``latest_checkpoint`` restore  |
| ``sync_params`` broadcast (``:131``)        | ``place_state_on_mesh``        |
| epoch loop + ``mpi_avg_grads`` (``:142-160``)| jitted DP step over the mesh  |
| rank-0 checkpoint (``:162-171``)            | process-0 ``save_checkpoint``  |
| rank-0 validation (``:173-185``)            | sharded batched eval           |
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from mpi_pytorch_tpu import checkpoint as ckpt
from mpi_pytorch_tpu.config import Config
from mpi_pytorch_tpu.data import DataLoader, load_manifests, manifest_fingerprint
from mpi_pytorch_tpu.models import create_model_bundle
from mpi_pytorch_tpu.obs import (
    FlightRecorder,
    Heartbeat,
    MetricsRegistry,
    SLOMonitor,
    StepHealth,
    Tracer,
    parse_rules,
)
from mpi_pytorch_tpu.parallel.collectives import LEDGER
from mpi_pytorch_tpu.parallel.mesh import (
    create_mesh,
    data_axis_names,
    data_axis_size,
    flat_mesh,
    is_hierarchical,
    pod_shape,
    shard_batch,
    zero_shard_axis,
)
from mpi_pytorch_tpu.train import elastic
from mpi_pytorch_tpu.train.state import (
    TrainState,
    make_optimizer,
    zero_shard_opt_state,
    zero_unshard_opt_state,
)
from mpi_pytorch_tpu.train.step import (
    bucket_overlap_frac,
    grad_bucket_plan,
    hier_dcn_overlap_frac,
    make_cached_eval_step,
    make_cached_train_step,
    make_eval_step,
    make_scanned_epoch,
    make_spmd_train_step,
    make_train_step,
    place_state_on_mesh,
)
from mpi_pytorch_tpu.utils import hardware as hw
from mpi_pytorch_tpu.utils.logging import MetricsWriter, init_logger, run_logger


@dataclass
class TrainSummary:
    epochs_run: int = 0
    final_loss: float = float("nan")
    val_accuracy: float | None = None
    epoch_times: list = field(default_factory=list)
    images_per_sec: float = 0.0
    checkpoint_path: str | None = None
    epoch_losses: list = field(default_factory=list)
    preempted: bool = False
    best_accuracy: float | None = None  # track_best: best val acc this run


class PreemptionGuard:
    """Graceful SIGTERM/SIGINT handling (SURVEY §5 failure-detection row).

    Cluster schedulers and TPU maintenance events deliver SIGTERM with a
    grace window; the reference's fail-stop MPI world dies mid-step and
    relies on a manual ``FROM_CHECKPOINT`` restart. Here the FIRST signal
    only sets a flag that the train loop polls — the run stops at the next
    safe boundary, saves any unsaved completed-epoch progress, drains the
    in-flight async checkpoint write, and returns normally with
    ``summary.preempted=True`` (exit code 0, auto-resume picks up the saved
    epoch). A SECOND signal restores the previous handler and re-raises it —
    the escape hatch if the graceful drain itself wedges.

    Installed only from the main thread (Python restricts ``signal.signal``
    to it); elsewhere the guard is inert and the signals keep their prior
    behavior."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self) -> None:
        self.triggered = False
        self._previous: dict[int, Any] = {}

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for sig in self.SIGNALS:
                self._previous[sig] = signal.signal(sig, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def _handle(self, signum, frame) -> None:
        if self.triggered:  # second signal: defer to the original behavior
            prev = self._previous.get(signum)
            signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
            signal.raise_signal(signum)
            return
        self.triggered = True


def _global_max(value: float, mesh) -> float:
    """Tiny all-reduce: the max of every process's ``value`` over the whole
    mesh (single-process: identity). The one collective that decisions read
    off the host side go through — anything that gates entering a collective
    (stop flags, best-accuracy init) must agree across processes."""
    if jax.process_count() == 1:
        return value
    from jax.sharding import NamedSharding, PartitionSpec as P

    local = np.full((jax.local_device_count(),), value, np.float32)
    sharding = NamedSharding(mesh, P(tuple(mesh.axis_names)))  # 1-D over all devices
    vals = jax.make_array_from_process_local_data(sharding, local)
    return float(jnp.max(vals))


def _stop_agreed(stop: bool, mesh) -> bool:
    """Epoch-boundary stop decision: EITHER all processes break before the
    next epoch or none do — a host stopping unilaterally would leave the
    others blocked in the next collective step. ``stop`` is this process's
    local verdict (the watchdog's poll of SIGTERM/sentinel/health streaks)."""
    return _global_max(1.0 if stop else 0.0, mesh) > 0.0


def _p0_scalar(value: float, mesh) -> float:
    """Process 0's ``value`` on every process: non-0 processes contribute
    -inf to the global max. Used where a value read from process 0's
    filesystem (e.g. the best.json marker) feeds a decision that gates a
    collective — every process must start from the same number even when
    the checkpoint dir is not a shared filesystem."""
    return _global_max(value if jax.process_index() == 0 else float("-inf"), mesh)


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


def build_training(cfg: Config, mesh=None):
    """Construct (mesh, bundle, state, loaders, step fns) for cfg — shared by
    the trainer, the eval pipeline, and the graft entry points."""
    mesh = mesh or create_mesh(cfg.mesh)
    compute_dtype = _dtype(cfg.compute_dtype)

    train_manifest, test_manifest = load_manifests(cfg)
    # Per-host sharding ≙ rank-0 scatter (main.py:84-91): host p reads only
    # its own shard; no coordinator, no pickled dataframes over the wire.
    host_shard = train_manifest.shard(jax.process_count(), jax.process_index())

    if cfg.batch_size % jax.process_count() != 0:
        raise ValueError(
            f"global batch {cfg.batch_size} not divisible by {jax.process_count()} hosts"
        )
    data_size = data_axis_size(mesh)
    if cfg.batch_size % data_size != 0:
        raise ValueError(
            f"global batch {cfg.batch_size} not divisible by data-parallel size "
            f"{data_size}; sharding the batch over the "
            f"'{'×'.join(data_axis_names(mesh))}' ax{'es' if is_hierarchical(mesh) else 'is'} "
            "requires even division"
        )
    host_batch = cfg.batch_size // jax.process_count()
    if cfg.accum_steps > 1 and (cfg.batch_size // cfg.accum_steps) % data_size != 0:
        raise ValueError(
            f"microbatch {cfg.batch_size}/{cfg.accum_steps} not divisible by "
            f"data-parallel size {data_size}"
        )

    train_loader = DataLoader(
        host_shard,
        batch_size=host_batch,
        image_size=cfg.image_size,
        shuffle=cfg.shuffle,
        seed=cfg.seed,
        drop_remainder=cfg.drop_remainder,
        synthetic=cfg.synthetic_data,
        num_workers=cfg.loader_workers,
        prefetch=cfg.prefetch_batches,
        image_dtype=cfg.input_dtype,
        native_decode=cfg.native_decode,
        decode_prescale=cfg.decode_prescale,
        host_cache=cfg.host_cache,
        packed_dir=cfg.packed_dir,
        max_bad_samples=cfg.max_bad_samples,
        quarantine_file=cfg.quarantine_file,
    )

    bundle, variables = create_model_bundle(
        cfg.model_name,
        cfg.num_classes,
        feature_extract=cfg.feature_extract,
        use_pretrained=cfg.use_pretrained,
        rng=jax.random.PRNGKey(cfg.seed),
        image_size=cfg.image_size[0],
        dtype=compute_dtype,
        param_dtype=jnp.float32,
        # Sync-BN: in spmd mode the axis name must be bound inside shard_map;
        # in auto mode BN already normalizes over the logical global batch
        # (the compiler inserts the cross-device mean), so no axis is needed.
        # Nested meshes sync over both data factors (flax forwards the
        # tuple to lax.pmean unchanged).
        bn_axis_name=(
            (data_axis_names(mesh) if is_hierarchical(mesh) else mesh.axis_names[0])
            if (cfg.sync_batchnorm and cfg.spmd_mode) else None
        ),
        pretrained_dir=cfg.pretrained_dir,
        remat_blocks=(cfg.remat == "blocks"),
        sp_strategy=cfg.sp_strategy,
        sp_mesh=flat_mesh(mesh, "seq") if cfg.sp_strategy != "none" else None,
        ep_mesh=flat_mesh(mesh, "expert") if cfg.expert_parallel else None,
        attn_impl=cfg.attn_impl,
        qkv_fused=cfg.qkv_fused,
        stem_s2d=cfg.stem_s2d,
        fused_stem=cfg.fused_stem,
        # Multi-chip fused kernels: the model shard_maps the Mosaic calls
        # (fused stem, fused-small attention) over the mesh's data axis
        # (ops/fused_stem.py / ops/fused_attention_small.py, Multi-chip).
        # Threaded in spmd mode too: inside the spmd step's shard_map the
        # wrappers detect the bound axis and run the per-shard call
        # directly, while spmd-mode VALIDATION (plain-jit eval over the
        # same model) still gets the partitioned call.
        dp_mesh=mesh if (cfg.fused_stem or cfg.attn_impl == "fused-small") else None,
    )
    # Total optimizer steps for cosine-style schedules: the globally-computed
    # per-epoch step count (identical on every host) x epochs.
    total_steps = (
        global_step_count(len(train_manifest), host_batch, cfg.drop_remainder)
        * cfg.num_epochs
    )
    tx = make_optimizer(
        cfg.learning_rate,
        bundle.trainable_mask,
        optimizer=cfg.optimizer,
        lr_schedule=cfg.lr_schedule,
        warmup_steps=cfg.warmup_steps,
        total_steps=total_steps,
        weight_decay=cfg.weight_decay,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply,
        variables=variables,
        tx=tx,
        rng=jax.random.PRNGKey(cfg.seed + 1),
    )
    if cfg.pp_stages > 1:
        # PP is an execution strategy, not a different model: swap the
        # apply_fn for the pipelined forward over the SAME param tree
        # (parallel/pp_vit.py), and every step flavor keyed on
        # state.apply_fn — streaming, cached, scanned-epoch, eval —
        # pipelines from here on.
        from mpi_pytorch_tpu.parallel.pp_vit import pp_apply_from_config

        state = state.replace(
            apply_fn=pp_apply_from_config(
                cfg, bundle.model, mesh, remat=(cfg.remat == "blocks")
            )
        )
    return mesh, bundle, state, (train_manifest, test_manifest, train_loader)


def pad_batch(images: np.ndarray, labels: np.ndarray, target: int):
    """Pad a tail batch to the static ``target`` rows; label -1 marks padding,
    which the loss/accuracy ops mask out (ops/losses.py). Static shapes mean
    XLA never recompiles, and no images are dropped (the reference's
    DataLoader keeps tail batches too, ``main.py:99-102``).

    Padding rows repeat real rows (cyclically) rather than injecting zero
    images: the loss masks them either way, but during training BatchNorm
    batch statistics span the whole padded batch, and repeated real rows keep
    those stats unbiased in expectation where zero rows would skew them
    (the reference instead trains on the smaller real tail batch)."""
    pad = target - images.shape[0]
    if pad <= 0:
        return images, labels
    images = np.concatenate([images, _cyclic_fill(images, pad)])
    labels = np.concatenate([labels, np.full(pad, -1, labels.dtype)])
    return images, labels


def _cyclic_fill(images: np.ndarray, n: int) -> np.ndarray:
    """``n`` rows of real image content, repeating ``images`` cyclically
    (zeros only when there are no real rows at all) — the shared fill
    strategy of ``pad_batch`` and ``synchronized_batches``."""
    if images.shape[0] == 0:
        return np.zeros((n, *images.shape[1:]), images.dtype)
    return images[np.resize(np.arange(images.shape[0]), n)]


def global_step_count(total_examples: int, host_batch: int, drop_remainder: bool) -> int:
    """Number of steps EVERY host must run per epoch, computed from global
    quantities so it is identical on all hosts.

    Per-host shards come from ``np.array_split`` semantics (manifest.shard),
    so shard sizes differ by up to 1 across hosts. Each step is a global SPMD
    program: a host running one extra (or one fewer) step than its peers
    deadlocks the collective. With drop_remainder the count is what the
    *smallest* shard yields (larger shards truncate); without, it is what the
    *largest* shard yields (exhausted shards feed all-padding batches)."""
    procs = jax.process_count()
    if drop_remainder:
        return (total_examples // procs) // host_batch
    largest = -(-total_examples // procs)
    return -(-largest // host_batch)


def data_cursor(
    cfg: Config, fingerprint: str, n_steps: int, next_epoch: int, step_in_epoch: int
) -> dict:
    """The exact-step resume cursor stamped into every checkpoint's topology
    sidecar (ISSUE 10): WHERE the run continues — ``(epoch, step_in_epoch)``
    in the deterministic global walk — plus everything that must still hold
    for that offset to mean the same samples: the shuffle discipline
    (seed/shuffle), the global batch and per-epoch step count (steps ×
    global batch is the topology-invariant sample count), the host count
    (per-host shards derive from it on the streaming path), and the global
    train manifest's fingerprint. ``validate_cursor`` checks each field and
    falls back to epoch replay on any mismatch — the cursor can be ignored,
    never silently misaligned."""
    return {
        "epoch": int(next_epoch),
        "step_in_epoch": int(step_in_epoch),
        "seed": int(cfg.seed),
        "shuffle": bool(cfg.shuffle),
        "global_batch": int(cfg.batch_size),
        "drop_remainder": bool(cfg.drop_remainder),
        "processes": int(jax.process_count()),
        "steps_per_epoch": int(n_steps),
        "manifest_fingerprint": fingerprint,
    }


def validate_cursor(
    cursor, *, cfg: Config, fingerprint: str, n_steps: int, start_epoch: int
) -> tuple[int, str | None]:
    """``(start_step, None)`` when ``cursor`` still describes this run's
    data walk, else ``(0, why)`` — the caller logs the typed warning and
    replays the epoch (today's behavior), never silently misaligning."""
    if not isinstance(cursor, dict):
        return 0, "no data cursor in the checkpoint's topology manifest"
    expected = {
        "epoch": start_epoch,
        "seed": int(cfg.seed),
        "shuffle": bool(cfg.shuffle),
        "global_batch": int(cfg.batch_size),
        "drop_remainder": bool(cfg.drop_remainder),
        "processes": int(jax.process_count()),
        "steps_per_epoch": int(n_steps),
        "manifest_fingerprint": fingerprint,
    }
    for key, want in expected.items():
        got = cursor.get(key)
        if got != want:
            return 0, f"cursor {key}={got!r} != current {want!r}"
    step = int(cursor.get("step_in_epoch", 0))
    if not 0 <= step < max(n_steps, 1):
        return 0, f"cursor step_in_epoch={step} outside 0..{n_steps - 1}"
    if step and cfg.scan_epoch:
        # A partial scanned epoch would need a differently-shaped scan
        # (one extra compile for a state the scan path can never itself
        # produce — scans never stop mid-epoch). Replay instead.
        return 0, "mid-epoch cursor with scan_epoch=True (scan is all-or-nothing)"
    return step, None


def _abort_skip_limit(metrics, epoch: int, streak: int, limit: int) -> None:
    """``--bad-step-policy skip`` ran out of patience: N consecutive
    non-finite updates were discarded, so the divergence is systematic, not
    transient — record it and abort (the same typed error the sentinel
    raises, so callers handle both abort paths uniformly)."""
    from mpi_pytorch_tpu.obs.health import NonFiniteLossError

    metrics.write(
        {
            "kind": "anomaly", "reason": "skip_limit", "epoch": epoch,
            "detail": f"{streak} consecutive skipped steps hit "
                      f"max_skipped_steps={limit}",
        }
    )
    raise NonFiniteLossError(
        f"{streak} consecutive non-finite steps were skipped (epoch {epoch}) "
        f"— hit --max-skipped-steps={limit}; the divergence is systematic, "
        "aborting instead of discarding updates forever"
    )


def synchronized_batches(
    loader: DataLoader, epoch: int, n_steps: int, start_step: int = 0
):
    """Yield exactly ``n_steps - start_step`` (images, labels) host-batches
    from ``loader`` — steps ``start_step..n_steps-1`` of the epoch — padding
    with all-padding batches (every label -1) once the local shard is
    exhausted and truncating any surplus, so every host issues the same
    number of collective steps (see ``global_step_count``). ``start_step``
    is the exact-step resume fast-forward: the loader skips the consumed
    prefix of its deterministic ``(seed, epoch)`` order without decoding it.

    Filler batches repeat the images of the last REAL batch (labels all -1):
    the loss masks them either way, but BatchNorm batch statistics span
    whatever images the step sees, so filler must be real image content, not
    zeros — the same reasoning as ``pad_batch``."""
    it = iter(loader.epoch(epoch, start_batch=start_step))
    all_pad = np.full((loader.batch_size,), -1, np.int32)
    last_images = None
    try:
        for _ in range(start_step, n_steps):
            batch = next(it, None)
            if batch is not None:
                last_images = batch[0]
                yield batch
            else:
                if last_images is None:  # empty local shard: no real rows exist
                    last_images = np.zeros(
                        (0, *loader.image_size, 3), loader.image_dtype
                    )
                yield (_cyclic_fill(last_images, loader.batch_size), all_pad)
    finally:
        if hasattr(it, "close"):
            it.close()  # stops the producer thread on early exit / truncation


def cached_index_batches(
    cfg: Config, n: int, host_batch: int, epoch: int, n_steps: int,
    shuffle: bool | None = None, start_step: int = 0,
):
    """Per-epoch (idx [B] int32, valid [B] bool) batches for the
    device-cache path. The permutation uses the same ``(seed, epoch)`` rng
    discipline as ``DataLoader.epoch``, so a cached run and a streaming run
    walk the data in the same order; tail indices repeat real rows
    (the ``_cyclic_fill`` policy) with ``valid=False``. ``shuffle=False``
    gives the ordered walk the cached eval path uses; ``start_step`` is the
    exact-step resume fast-forward (the consumed prefix of the permutation
    is simply not yielded)."""
    from mpi_pytorch_tpu.data.pipeline import epoch_order

    order = epoch_order(cfg.seed, epoch, n, cfg.shuffle if shuffle is None else shuffle)
    for step_i in range(start_step, n_steps):
        idx = order[step_i * host_batch : (step_i + 1) * host_batch]
        valid = np.ones(len(idx), bool)
        pad = host_batch - len(idx)
        if pad > 0:
            fill = np.resize(idx, pad) if len(idx) else np.zeros(pad, order.dtype)
            idx = np.concatenate([idx, fill])
            valid = np.concatenate([valid, np.zeros(pad, bool)])
        yield idx.astype(np.int32), valid


def _state_shardings(state):
    """The placed state's shardings, used to PIN the train step's output
    state layout to its input layout. Without this the AOT executable's
    output shardings are compiler-chosen, and with ZeRO-sharded moments XLA
    happily emits data-sharded *params* — which the next call then rejects,
    since AOT executables do not auto-reshard their inputs."""
    return jax.tree_util.tree_map(lambda x: x.sharding, state)


def device_prefetch(batches, mesh, host_batch: int, depth: int = 2):
    """Double-buffered host→device transfer: pad + ``shard_batch`` each
    host batch ``depth`` steps ahead of the consumer. ``device_put`` is
    asynchronous, so the H2D copy for batch N+1 overlaps the compute of
    batch N — the overlap the reference's 4-stage MPI pipeline bought with
    dedicated ranks (``evaluation_pipeline.py:53-129``), at zero process
    cost."""
    from collections import deque

    buf = deque()
    for images, labels in batches:
        images, labels = pad_batch(images, labels, host_batch)
        buf.append(shard_batch((images, labels), mesh))
        if len(buf) > depth:
            yield buf.popleft()
    while buf:
        yield buf.popleft()


def build_device_cache(cfg: Config, manifest, loader: DataLoader, mesh):
    """Materialize the train split as a device-resident dataset with rows
    SHARDED over the data axis — per-device HBM is ``dataset/n_data``, not a
    full replica per chip — plus replicated (tiny) labels. One decode pass
    in manifest order; the per-epoch shuffle happens on indices instead, and
    the step gathers batch rows across shards (``step._sharded_cache_take``).

    ``manifest`` is the GLOBAL train manifest: global row i is dataset row i
    on every host, so the identical seeded index permutation each host draws
    refers to the same images. Each host decodes exactly the contiguous row
    range its local devices hold (data is the mesh's major axis), which is
    what makes the cache build itself scale with the host count. Rows are
    padded up to a multiple of the data-axis size; padding rows sit past the
    real row count and are never indexed."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    data_axis = mesh.axis_names[0]
    n_data = mesh.shape[data_axis]
    n = len(manifest)
    padded = -(-n // n_data) * n_data
    shape = (padded, *loader.image_size, 3)
    sharding = NamedSharding(mesh, P(data_axis))

    # This host's addressable slice of the sharded rows: contiguous because
    # ``data`` is the leading (process-major) mesh axis.
    imap = sharding.addressable_devices_indices_map(shape)
    lo = min((s[0].start or 0) for s in imap.values())
    hi = max((s[0].stop if s[0].stop is not None else padded) for s in imap.values())
    real_hi = min(hi, n)

    # Preallocate and fill in place: np.concatenate over a parts list would
    # transiently hold the slice twice, at exactly the scale (GBs) this
    # feature targets. Zeros beyond real_hi are the never-indexed padding.
    local = np.zeros((hi - lo, *loader.image_size, 3), loader.image_dtype)
    labels_np = manifest.labels.astype(np.int32)
    if real_hi > lo:
        ordered = DataLoader(
            manifest.select(np.arange(lo, real_hi)),
            batch_size=loader.batch_size,
            image_size=loader.image_size,
            shuffle=False,
            drop_remainder=False,
            synthetic=loader.synthetic,
            num_workers=loader.num_workers,
            prefetch=loader.prefetch,
            image_dtype=str(np.dtype(loader.image_dtype)),
            native_decode=loader.native_decode,
            decode_prescale=loader.decode_prescale,
            packed_dir=loader.packed_dir,
            max_bad_samples=loader.max_bad_samples,
            quarantine_file=loader.quarantine_file,
        )
        ordered.metrics = loader.metrics
        row = 0
        for batch_images, _ in ordered.epoch(0):
            local[row : row + batch_images.shape[0]] = batch_images
            row += batch_images.shape[0]
        assert row == real_hi - lo, (row, lo, real_hi)
        if ordered._quarantined:
            if jax.process_count() > 1:
                # Each host decodes only its own row range, so a per-host
                # label mask would make the REPLICATED labels array differ
                # across hosts — silent divergence inside every collective
                # step. Abort loudly instead (the quarantine trail names
                # the files); multi-host runs must fix the data or take
                # the streaming/host-cache path, whose masking is local.
                from mpi_pytorch_tpu.data.pipeline import BadSampleLimitError

                raise BadSampleLimitError(
                    f"{len(ordered._quarantined)} sample(s) quarantined "
                    "while building the multi-host device cache — per-host "
                    "label masking cannot stay consistent across hosts; "
                    "repair/remove the corrupt files (see the quarantine "
                    "log) or drop --device-cache"
                )
            # Quarantined rows hold substitute pixels — mask their labels.
            labels_np = labels_np.copy()
            labels_np[lo + np.fromiter(ordered._quarantined, int)] = -1

    rep = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        dataset = jax.device_put(local, sharding)
        labels = jax.device_put(labels_np, rep)
    else:
        dataset = jax.make_array_from_process_local_data(sharding, local)
        labels = jax.make_array_from_process_local_data(rep, labels_np)
    jax.block_until_ready(dataset)
    return dataset, labels


def make_eval_loader(cfg: Config, manifest, host_cache: bool = False) -> DataLoader:
    """The eval/validation DataLoader over this host's shard of ``manifest``.
    ``host_cache`` defaults OFF: a one-shot evaluation streams through the
    data once, so pre-decoding a full shard-sized cache would cost strictly
    more. Per-epoch validation passes ``cfg.host_cache`` and reuses ONE
    loader across epochs (the loader owns the cache) — and under
    ``val_on_train`` it adopts the train loader's cache outright."""
    return DataLoader(
        manifest.shard(jax.process_count(), jax.process_index()),
        batch_size=cfg.batch_size // jax.process_count(),
        image_size=cfg.image_size,
        shuffle=False,
        drop_remainder=False,
        synthetic=cfg.synthetic_data,
        num_workers=cfg.loader_workers,
        prefetch=cfg.prefetch_batches,
        image_dtype=cfg.input_dtype,
        native_decode=cfg.native_decode,
        decode_prescale=cfg.decode_prescale,
        host_cache=host_cache,
        packed_dir=cfg.packed_dir,
        max_bad_samples=cfg.max_bad_samples,
        quarantine_file=cfg.quarantine_file,
    )


def evaluate_manifest(
    cfg: Config, state: TrainState, mesh, manifest, loader: DataLoader | None = None
) -> tuple[float, float]:
    """Batched sharded eval over a manifest → (accuracy, mean_loss).
    ≙ the rank-0 validation loop (``main.py:173-185``), but using every chip.
    Pass a ``make_eval_loader`` instance to reuse its host cache across calls."""
    eval_step = make_eval_step(_dtype(cfg.compute_dtype))
    host_batch = cfg.batch_size // jax.process_count()
    if loader is None:
        loader = make_eval_loader(cfg, manifest)
    n_steps = global_step_count(len(manifest), host_batch, drop_remainder=False)
    return _accumulate_eval(
        eval_step(state, shard_batch(pad_batch(images, labels, host_batch), mesh))
        for images, labels in synchronized_batches(loader, 0, n_steps)
    )


def _accumulate_eval(metric_batches) -> tuple[float, float]:
    """Fold per-batch eval metrics into (accuracy, mean_loss) — the one
    accounting shared by the streaming and cached eval paths."""
    correct = total = 0
    loss_sum = 0.0
    for m in metric_batches:
        correct += int(m["correct"])
        total += int(m["count"])
        loss_sum += float(m["loss"])
    if total == 0:
        return 0.0, float("nan")
    return correct / total, loss_sum / total


def evaluate_cached(cfg: Config, state: TrainState, mesh, dataset, labels) -> tuple[float, float]:
    """Batched eval over a DEVICE-RESIDENT dataset → (accuracy, mean_loss).
    Same semantics as ``evaluate_manifest`` but zero host decode / H2D per
    call — per-epoch validation over an HBM-cached val set (with
    ``val_on_train=True``, the reference's default, the val set IS the
    already-cached train set)."""
    eval_step = make_cached_eval_step(mesh, _dtype(cfg.compute_dtype))
    # Real row count from the labels: the sharded dataset's row dim carries
    # divisibility padding past it (build_device_cache) that must not be
    # evaluated. Index batches are global and identical on every host.
    n = int(labels.shape[0])
    n_steps = -(-n // cfg.batch_size)
    return _accumulate_eval(
        eval_step(state, dataset, labels, idx, valid)
        for idx, valid in cached_index_batches(
            cfg, n, cfg.batch_size, epoch=0, n_steps=n_steps, shuffle=False
        )
    )


def train(cfg: Config) -> TrainSummary:
    from mpi_pytorch_tpu.parallel.distributed import maybe_initialize_distributed

    from mpi_pytorch_tpu.config import apply_runtime_flags

    maybe_initialize_distributed()
    apply_runtime_flags(cfg)
    logger = init_logger("MPT", cfg.log_file)
    metrics = MetricsWriter(cfg.metrics_file)
    # Run telemetry (obs/): host-side trace spans, per-step health records,
    # the NaN sentinel, and the multi-host straggler heartbeat. All inert
    # unless their knobs are set (the sentinel's epoch check is free).
    tracer = Tracer(cfg.trace_file)
    # Anomaly flight recorder (obs/flight.py): tap the metrics writer so
    # every record on EVERY process enters the ring (only process 0's
    # writer persists the stream), and any fault/alert record dumps it.
    flight = None
    if cfg.flight_dir:
        flight = FlightRecorder(
            cfg.flight_dir, capacity=cfg.flight_records,
            profile_window_s=cfg.flight_profile_window_s,
        )
        metrics = flight.tap(metrics)
    # Live metrics registry + SLO monitor (obs/metrics.py, obs/monitor.py):
    # built only when a live consumer is configured — the default hot path
    # never touches either.
    registry = monitor = None
    if cfg.slo_rules or cfg.metrics_every_steps:
        registry = MetricsRegistry()
    if cfg.slo_rules:
        monitor = SLOMonitor(
            registry, parse_rules(cfg.slo_rules), metrics=metrics,
            preempt_path=cfg.preempt_file, tracer=tracer, logger=logger,
        )
    # With a bad-step POLICY armed (skip/rollback) the sentinel's hard
    # abort is replaced by the policy: the non-finite step is the event the
    # policy handles, not a reason to crash (the policy's own bounds —
    # max_skipped_steps / max_rollbacks — are the new aborts).
    health = StepHealth(
        metrics, step_metrics=cfg.step_metrics,
        nan_sentinel=cfg.nan_sentinel and cfg.bad_step_policy == "abort",
        tracer=tracer, registry=registry,
    )
    heartbeat = Heartbeat(
        metrics, every_steps=cfg.heartbeat_every_steps,
        threshold=cfg.straggler_threshold, batch_images=cfg.batch_size,
        tracer=tracer, registry=registry,
    )
    if heartbeat.enabled and cfg.device_cache and cfg.scan_epoch:
        # The scan runs the whole epoch on device — there are no per-step
        # host returns to beat on. Surface it instead of silently recording
        # nothing (the fused-head-eval lesson, advisor r5).
        run_logger().warning(
            "heartbeat_every_steps=%d has no effect with scan_epoch=True "
            "(the epoch is one device-side scan; no per-step host "
            "boundaries to exchange step times at)",
            cfg.heartbeat_every_steps,
        )
        heartbeat.enabled = False
    if registry is not None and cfg.metrics_every_steps and cfg.device_cache and cfg.scan_epoch:
        # Same silent-degrade class: the scan path has no per-step host
        # boundaries, so the snapshot cadence never advances — only the
        # run-end snapshot lands. (slo_rules + scan_epoch is already a
        # config ERROR; a reduced snapshot cadence merely degrades.)
        run_logger().warning(
            "metrics_every_steps=%d has no per-step cadence with "
            "scan_epoch=True (the epoch is one device-side scan); only "
            "the final kind='metrics' snapshot will be written",
            cfg.metrics_every_steps,
        )
    # Per-step telemetry must observe step COMPLETION, not dispatch: block
    # on the step's metrics before timestamping (documented cost of
    # step_metrics/heartbeat; registry step-time gauges/histograms must be
    # completion times too, so a live registry also syncs; the default
    # loop stays fully async). A bad-step policy also syncs: the host must
    # observe every step's loss/grad-norm verdict to count skips or
    # trigger a rollback.
    telemetry_sync = (
        health.enabled or heartbeat.enabled or registry is not None
        or cfg.bad_step_policy != "abort"
    )
    try:
        return _train_impl(
            cfg, logger, metrics, tracer, health, heartbeat, telemetry_sync,
            registry, monitor, flight,
        )
    except BaseException:
        # A failure anywhere — including build/cache/compile, BEFORE the
        # epoch loop's own handler exists — must still flush the buffered
        # spans: the aborted run is exactly the one whose trace is needed.
        # The flight recorder dumps its last-moments ring the same way.
        try:
            tracer.close()
        except BaseException as terr:
            logger.warning("trace write also failed: %s", terr)
        if flight is not None:
            try:
                flight.dump("crash")
                flight.close()
            except BaseException as ferr:
                logger.warning("flight-recorder dump also failed: %s", ferr)
        raise


def _train_impl(
    cfg: Config, logger, metrics, tracer, health, heartbeat, telemetry_sync,
    registry=None, monitor=None, flight=None,
) -> TrainSummary:
    with tracer.span("build"):
        mesh = None
        if cfg.from_checkpoint:
            # Resume side: backend init retries with bounded backoff — a
            # transiently wedged backend (bench history r02/r05) must cost
            # attempts, not the auto-resume (train/elastic.py).
            mesh = elastic.with_retries(
                lambda: create_mesh(cfg.mesh),
                what="backend init (mesh build)",
                retries=cfg.resume_retries, backoff_s=cfg.resume_backoff_s,
                logger=logger,
            )
        mesh, bundle, state, (train_manifest, test_manifest, loader) = build_training(
            cfg, mesh=mesh
        )
    logger.info(
        "world: %d process(es), %d device(s), mesh %s",
        jax.process_count(), jax.device_count(), dict(mesh.shape),
    )
    logger.info(
        "model %s | %d classes | global batch %d | shard %d images (≙ scatter, main.py:84-91)",
        cfg.model_name, cfg.num_classes, cfg.batch_size, len(loader.manifest),
    )

    # The exact-step resume cursor is defined over the GLOBAL train
    # manifest (global-sample space — topology-invariant); the loader gets
    # the metrics writer so decode quarantines land in the stream.
    fingerprint = manifest_fingerprint(train_manifest)
    loader.metrics = metrics

    start_epoch = 0
    resumed = False
    resume_manifest = None
    resume_was_dirty = False
    # ZeRO shard count: the WITHIN-POD (ici) size on a nested mesh — shards
    # place inside a pod so the param all_gather never crosses the DCN
    # (train/state.py zero_shard_opt_state); the whole data axis when flat.
    zero_shards_to = (
        zero_shard_axis(mesh)[1] if (cfg.spmd_mode and cfg.zero_opt_state) else 0
    )
    if cfg.from_checkpoint:
        # Elastic restore (train/elastic.py): newest LOADABLE checkpoint
        # (corrupt files log a kind="anomaly" record and fall back to the
        # previous one), topology manifest compared against the current
        # mesh, kind="resume" record written — the self-healing form of
        # the reference's manual FROM_CHECKPOINT restart (main.py:127-130).
        res = elastic.restore_latest(
            cfg.checkpoint_dir, state, mesh, metrics=metrics, logger=logger,
            zero_shards_to=zero_shards_to,
        )
        if res is not None:
            state, start_epoch, last_loss, _resume = res
            resumed = True
            resume_manifest = _resume.get("manifest")
            resume_was_dirty = os.path.exists(_resume["path"] + ".dirty")
            start_epoch += 1
            logger.info(
                "resumed from %s (epoch %d, loss %.4f)",
                _resume["path"], start_epoch, last_loss,
            )
        else:
            logger.info("from_checkpoint=True but no checkpoint found; fresh start")

    # With ZeRO opt-state sharding the optimizer tree must NOT go through
    # the replicated placement below: that would device_put the full
    # unsharded 2×params moments onto every device — exactly the transient
    # HBM spike the sharding exists to avoid — before the [P, chunk]
    # reshard even runs. Detach it here and hand the raw (host, on resume)
    # tree straight to zero_shard_opt_state, whose bounded per-row path
    # then never sees more than one chunk per device.
    defer_zero_opt = cfg.spmd_mode and cfg.zero_opt_state
    raw_opt_state = state.opt_state
    if defer_zero_opt:
        state = state.replace(opt_state=())
    if resumed:
        # Reshard-on-load placement, retried: the restored host state is
        # re-placed onto THIS mesh (whatever its shape), with device_put
        # wrapped in the same bounded retry+backoff as backend init.
        state = elastic.with_retries(
            lambda: elastic.checked_place(
                state, mesh, zero_optimizer=cfg.zero_optimizer, fsdp=cfg.fsdp
            ),
            what="state placement (device_put)",
            retries=cfg.resume_retries, backoff_s=cfg.resume_backoff_s,
            logger=logger,
        )
    else:
        state = place_state_on_mesh(
            state, mesh, zero_optimizer=cfg.zero_optimizer, fsdp=cfg.fsdp
        )
    if defer_zero_opt:
        state = state.replace(opt_state=raw_opt_state)
    # ZeRO opt-state sharding (spmd mode): capture the UNSHARDED optimizer
    # layout first (eval_shape: shapes only, zero device memory) — it is the
    # gather-on-save template that keeps the on-disk checkpoint format
    # identical to an unsharded run's — then repartition every moment leaf
    # [P, chunk] over the data axis (train/state.py zero_shard_spec).
    opt_template = None
    if cfg.spmd_mode and cfg.zero_opt_state:
        opt_template = jax.eval_shape(state.tx.init, state.params)
        state = state.replace(opt_state=zero_shard_opt_state(state.opt_state, mesh))
        zero_axis_name, n_zero = zero_shard_axis(mesh)
        moment_bytes = sum(
            s.data.nbytes
            for leaf in jax.tree_util.tree_leaves(state.opt_state)
            if hasattr(leaf, "addressable_shards") and leaf.ndim > 0
            for s in leaf.addressable_shards[:1]
        )
        logger.info(
            "ZeRO opt-state sharding: moments partitioned 1/%d over '%s'%s "
            "(%.1f MB/device)",
            n_zero, zero_axis_name,
            " (within-pod: the param all_gather never crosses the DCN)"
            if is_hierarchical(mesh) else "",
            moment_bytes / 1e6,
        )

    def _saveable(st: TrainState) -> TrainState:
        """The checkpoint view of the state: with ZeRO-sharded optimizer
        state, gather-on-save to the unsharded host layout (one leaf at a
        time) so the file format never depends on the run's sharding."""
        if opt_template is None:
            return st
        return st.replace(
            opt_state=zero_unshard_opt_state(st.opt_state, opt_template)
        )

    # Topology manifest stamped onto every checkpoint this run writes
    # (JSON sidecar, checkpoint.write_manifest): the world shape + ZeRO
    # shard layout an elastic restore reshards FROM (train/elastic.py).
    topology = elastic.topology_manifest(
        mesh,
        zero_opt_state=bool(zero_shards_to),
        spmd_mode=cfg.spmd_mode,
        opt_template=opt_template,
    )

    host_batch = cfg.batch_size // jax.process_count()

    # AOT-compile the step on the static batch shape: one compile serves the
    # whole run, and the executable's cost analysis gives exact FLOPs/step for
    # MFU logging (SURVEY §5 — the reference has only wall-clock timers).
    n_steps = global_step_count(len(train_manifest), host_batch, cfg.drop_remainder)
    dataset = labels_all = None
    val_loader = None  # built lazily, then reused so its host cache persists
    # Cached-mode index batches are GLOBAL (every host draws the identical
    # seeded permutation over the global manifest): one [B] index array per
    # step on all hosts, stepping over global rows.
    cache_batch = cfg.batch_size
    n_cache = len(train_manifest)
    # --bad-step-policy skip: the jitted step itself discards a non-finite
    # update (train/step.py _guard_bad_step); the host side only counts.
    bad_step_skip = cfg.bad_step_policy == "skip"
    if cfg.device_cache:
        # Step count over the GLOBAL walk (the streaming count derives from
        # per-host array_split shards and can differ by rounding off it).
        n_steps = (
            n_cache // cache_batch if cfg.drop_remainder else -(-n_cache // cache_batch)
        )
        with tracer.span("cache_build"):
            dataset, labels_all = build_device_cache(cfg, train_manifest, loader, mesh)
        n_data = mesh.shape[cfg.mesh.data_axis]
        logger.info(
            "device cache: %d images, rows sharded over %d device(s) "
            "(%.1f MB/device %s)",
            n_cache, n_data, dataset.nbytes / n_data / 1e6, dataset.dtype,
        )

    def build_compiled(st: TrainState):
        """AOT-compile the train step (scan-epoch mode: the whole-epoch
        scan) against ``st``'s placed layout → ``(compiled_step,
        flops_per_step)``. Factored out of the straight-line setup so a
        bad-step ROLLBACK that rebuilt the optimizer (--rollback-lr-backoff
        embeds a new LR in the step program) can recompile against the
        restored state; the default run calls it exactly once. The compile
        span opens here, AFTER the device-cache build — a span that
        swallowed the dataset decode would misattribute ingest time to XLA,
        the exact confusion the tracer exists to prevent."""
        span = tracer.begin("compile")
        try:
            if cfg.device_cache:
                # The per-step program is the FLOPs reference either way;
                # the scan mode reuses the Lowered (cost analysis needs no
                # backend compile) because XLA counts a scan body once
                # regardless of trip count.
                lowered_step = jax.jit(
                    make_cached_train_step(
                        mesh, _dtype(cfg.compute_dtype), remat=(cfg.remat == "full"),
                        bad_step_skip=bad_step_skip,
                    ),
                    donate_argnums=(0,), out_shardings=(_state_shardings(st), None),
                ).lower(
                    st, dataset, labels_all,
                    np.zeros((cache_batch,), np.int32), np.ones((cache_batch,), bool),
                )
                if cfg.scan_epoch:
                    epoch_fn = make_scanned_epoch(
                        mesh, _dtype(cfg.compute_dtype), remat=(cfg.remat == "full"),
                        bad_step_skip=bad_step_skip,
                    )
                    compiled = jax.jit(
                        epoch_fn, donate_argnums=(0,),
                        out_shardings=(_state_shardings(st), None),
                    ).lower(
                        st, dataset, labels_all,
                        np.zeros((n_steps, cache_batch), np.int32),
                        np.ones((n_steps, cache_batch), bool),
                    ).compile(compiler_options=cfg.parsed_compiler_options())
                    # Per-step FLOPs for the scan mode, without compiling a
                    # throwaway per-step executable. Two wrinkles: (a)
                    # Lowered.cost_analysis() runs BEFORE SPMD partitioning,
                    # so the per-step lowering gives WHOLE-program FLOPs
                    # (÷ device_count approximates per-device); (b) whether
                    # the compiled scan's cost analysis counts the body once
                    # or trip-count times is an XLA implementation detail
                    # (observed: once). Use the compiled scan's number,
                    # disambiguated against the lowered estimate.
                    est = hw.step_flops(lowered_step) / max(1, jax.device_count())
                    cand = hw.step_flops(compiled)
                    if cand > 0 and est > 0 and n_steps > 1:
                        flops = (
                            cand if abs(cand - est) <= abs(cand / n_steps - est)
                            else cand / n_steps
                        )
                    else:
                        flops = cand if cand > 0 else est
                    return compiled, flops
                compiled = lowered_step.compile(
                    compiler_options=cfg.parsed_compiler_options()
                )
                return compiled, hw.step_flops(compiled)
            step_fn = (
                make_spmd_train_step(
                    mesh, _dtype(cfg.compute_dtype), remat=(cfg.remat == "full"),
                    zero_opt_state=cfg.zero_opt_state,
                    grad_bucket_mb=cfg.grad_sync_buckets,
                    bad_step_skip=bad_step_skip,
                )
                if cfg.spmd_mode
                else make_train_step(
                    _dtype(cfg.compute_dtype), remat=(cfg.remat == "full"),
                    accum_steps=cfg.accum_steps, mesh=mesh,
                    bad_step_skip=bad_step_skip,
                )
            )
            # The sample must match the loader's batch dtype exactly — the
            # AOT executable is specialized on input avals.
            sample = shard_batch(
                (np.zeros((host_batch, *cfg.image_size, 3), loader.image_dtype),
                 np.zeros((host_batch,), np.int32)),
                mesh,
            )
            if cfg.spmd_mode:
                compiled = step_fn.lower(st, sample).compile(
                    compiler_options=cfg.parsed_compiler_options()
                )
            else:
                compiled = jax.jit(
                    step_fn, donate_argnums=(0,),
                    out_shardings=(_state_shardings(st), None),
                ).lower(st, sample).compile(
                    compiler_options=cfg.parsed_compiler_options()
                )
            return compiled, hw.step_flops(compiled)
        finally:
            tracer.end(span)

    # Per-axis collective-traffic ledger (ISSUE 15): bytes are booked at
    # TRACE time (shapes are static), so one reset + one lower = exactly
    # one step's ICI-vs-DCN traffic, attributable per collective op.
    LEDGER.reset()
    compiled_step, flops_per_step = build_compiled(state)
    traffic = LEDGER.snapshot() if cfg.spmd_mode else None
    if traffic is not None and (traffic["ici"]["ops"] or traffic["dcn"]["ops"]):
        tracer.instant(
            "collective_traffic",
            args={
                "ici_bytes_per_step": traffic["ici"]["bytes"],
                "dcn_bytes_per_step": traffic["dcn"]["bytes"],
                "dcn_by_op": traffic["dcn"]["by_op"],
            },
        )
        if registry is not None:
            registry.gauge("train/ici_bytes_per_step").set(traffic["ici"]["bytes"])
            registry.gauge("train/dcn_bytes_per_step").set(traffic["dcn"]["bytes"])
        if is_hierarchical(mesh):
            pods, ici = pod_shape(mesh)
            logger.info(
                "hierarchical sync (%d pod(s) × %d ici): %.2f MB/step ICI, "
                "%.3f MB/step DCN per device (cross-pod payload 1/%d of the "
                "gradient)",
                pods, ici, traffic["ici"]["bytes"] / 1e6,
                traffic["dcn"]["bytes"] / 1e6, ici,
            )

    # Exact-step resume (ISSUE 10): validate the restored checkpoint's data
    # cursor against THIS run's walk. A match fast-forwards the first
    # post-resume epoch past the consumed batches (zero replayed optimizer
    # steps); any mismatch writes a typed kind="anomaly" record and falls
    # back to today's epoch replay — the cursor can be ignored, never
    # silently misaligned.
    start_step = 0
    if resumed:
        cursor = (resume_manifest or {}).get("data_cursor")
        start_step, cursor_why = validate_cursor(
            cursor, cfg=cfg, fingerprint=fingerprint, n_steps=n_steps,
            start_epoch=start_epoch,
        )
        if cursor_why is not None and (cursor is not None or resume_was_dirty):
            metrics.write(
                {
                    "kind": "anomaly", "reason": "cursor_mismatch",
                    "epoch": start_epoch, "detail": cursor_why,
                }
            )
            logger.warning(
                "exact-step resume unavailable (%s) — replaying epoch %d "
                "from step 0%s", cursor_why, start_epoch,
                " (DIRTY checkpoint: the replay double-applies the partial "
                "epoch's updates)" if resume_was_dirty else "",
            )
        elif start_step:
            logger.info(
                "exact-step resume: continuing epoch %d at step %d "
                "(fast-forwarding %d consumed batch(es) without decoding)",
                start_epoch, start_step, start_step,
            )

    # Grad-sync bucket-plan telemetry (spmd + --grad-sync-buckets): one
    # instant span per bucket (bytes/leaves, in reverse-topo issue order)
    # and the static overlap_frac estimate stamped onto every step health
    # record — the plan the chip A/B (tools/bench_modes.py --levers)
    # measures against.
    _hier = is_hierarchical(mesh)
    if cfg.spmd_mode and cfg.grad_sync_buckets > 0:
        _plan = grad_bucket_plan(state.params, cfg.grad_sync_buckets)
        _overlap = bucket_overlap_frac(state.params, _plan)
        _dcn_overlap = hier_dcn_overlap_frac(state.params, _plan) if _hier else None
        _leaves = jax.tree_util.tree_leaves(state.params)
        _, _ici_size = pod_shape(mesh)
        for _order, _bucket in enumerate(_plan):
            _bytes = int(
                sum(_leaves[i].size * _leaves[i].dtype.itemsize for i in _bucket)
            )
            tracer.instant(
                "grad_bucket",
                args={"order": _order, "leaves": len(_bucket), "bytes": _bytes},
            )
            if _hier:
                # The bucket's CROSS-POD phase: issued the moment its
                # within-pod reduce-scatter lands, carrying 1/ici of the
                # bucket's bytes over the DCN — one instant per bucket so
                # a chip trace can line the phases up against backward.
                tracer.instant(
                    "dcn",
                    args={
                        "order": _order,
                        "bytes": _bytes // _ici_size,
                        "of_bucket_bytes": _bytes,
                    },
                )
        health.set_sync(overlap_frac=_overlap, dcn_overlap_frac=_dcn_overlap)
        if registry is not None:
            registry.gauge("train/overlap_frac").set(_overlap)
            if _dcn_overlap is not None:
                registry.gauge("train/dcn_overlap_frac").set(_dcn_overlap)
        logger.info(
            "grad-sync buckets: %d × ~%.0f MiB (reverse-topo issue order), "
            "%.0f%% of sync bytes overlap-eligible%s%s",
            len(_plan), cfg.grad_sync_buckets, 100.0 * _overlap,
            ", reduce-scatter (ZeRO slices)" if cfg.zero_opt_state else "",
            ", two-phase ICI/DCN (per-bucket cross-pod stage overlapped)"
            if _hier else "",
        )
    elif cfg.spmd_mode and _hier:
        # Hierarchical without buckets: the whole-tree sync is still
        # two-phase (DCN carries 1/ici of the payload), but its cross-pod
        # stage waits for the full backward — nothing to overlap, which
        # the stamped 0.0 makes visible rather than implicit.
        health.set_sync(dcn_overlap_frac=0.0)
    peak = hw.peak_bf16_tflops(jax.devices()[0])
    if heartbeat.enabled and heartbeat.every > n_steps:
        # Beats never span epoch boundaries (the window resets per epoch),
        # so an interval longer than the epoch would silently never fire —
        # the same silent-degrade class as the scan_epoch case above.
        run_logger().warning(
            "heartbeat_every_steps=%d exceeds the %d step(s) per epoch — no "
            "heartbeat will ever fire (beats never span epoch boundaries); "
            "lower it to at most the per-epoch step count",
            heartbeat.every, n_steps,
        )

    # Live-registry step instrumentation, pre-bound so the loop body does
    # no registry lookups; snapshot cadence counts STEPS (not wall time)
    # because the multi-host merge inside snapshot_record is a collective
    # every process must reach at the same step.
    h_step_ms = h_wait_ms = g_step_last = None
    if registry is not None:
        h_step_ms = registry.histogram("train/step_ms")
        h_wait_ms = registry.histogram("train/data_wait_ms")
        g_step_last = registry.gauge("train/step_ms_last")
    snapshot_merge = jax.process_count() > 1
    steps_since_snapshot = 0

    summary = TrainSummary()
    checkpointer = ckpt.AsyncCheckpointer()
    total_images = 0
    train_t0 = time.perf_counter()
    epoch_loss = float("nan")

    # SURVEY §5 observability: step-level XLA traces, viewable in TensorBoard
    # (the reference only has MPI.Wtime wall-clock pairs, main.py:145,158).
    profiling = bool(cfg.profile_dir)
    if profiling:
        jax.profiler.start_trace(cfg.profile_dir)

    # The guard stays installed through the preemption save and the final
    # checkpoint drain below: a FIRST signal arriving mid-drain is absorbed
    # (the run is already finishing), and only a SECOND signal falls through
    # to the previous handler — the escape hatch if the drain itself wedges.
    guard = PreemptionGuard()
    # Deterministic chaos, armed only via the MPT_FAULT_* env gates
    # (utils/env.py FAULT_GATES; driven by tools/inject_faults.py).
    faults = elastic.FaultInjector(metrics=metrics)
    if faults.active:
        logger.warning(
            "fault injection armed: kill_at_step=%d delay_step_ms=%d "
            "dcn_delay_ms=%d nonfinite_at_step=%d preempt_at_step=%d "
            "(MPT_FAULT_* gates)",
            faults.kill_at_step, faults.delay_ms, faults.dcn_delay_ms,
            faults.nonfinite_at_step, faults.preempt_at_step,
        )
    if faults.nonfinite_at_step and (
        cfg.device_cache or loader.image_dtype == np.dtype(np.uint8)
    ):
        logger.warning(
            "MPT_FAULT_NONFINITE_AT_STEP has no effect on this run: the "
            "gate NaN-poisons streaming float batches, and this run feeds "
            "%s", "device-cache indices" if cfg.device_cache else "uint8 pixels",
        )
    if faults.dcn_delay_ms and not _hier:
        logger.warning(
            "MPT_FAULT_DCN_DELAY_MS has no effect on this run: a flat mesh "
            "has no cross-pod phase to slow down (set --mesh-pods > 1)"
        )
    # The watchdog unifies every stop signal behind one poll: the guard's
    # SIGTERM flag, the MPT_PREEMPT_FILE sentinel, repeated health signals
    # (straggler beats / non-finite grad norms), and the injected-preempt
    # gate — each firing writes a kind="fault" record and stops the run at
    # the same safe boundary a SIGTERM would (train/elastic.py).
    watchdog = elastic.PreemptionWatchdog(
        guard,
        preempt_file=cfg.preempt_file,
        straggler_beats=cfg.preempt_straggler_beats,
        nonfinite_steps=cfg.preempt_nonfinite_steps,
        heartbeat=heartbeat, health=health, metrics=metrics, logger=logger,
        injector=faults,
    )
    # --- bad-step-policy state (ISSUE 10) ---------------------------------
    # skip: the step discards on device; the host counts the consecutive
    # streak (every host reads the same psum'd verdict, so the abort below
    # is agreed without a collective). rollback: the governor watches the
    # same host-read values and the trainer restores in-process.
    if cfg.bad_step_policy != "abort":
        logger.info(
            "bad-step policy '%s': the NaN sentinel's hard abort is "
            "replaced by the policy (per-step host sync enabled to observe "
            "loss/grad norm)", cfg.bad_step_policy,
        )
    skip_streak = 0
    steps_skipped_total = 0
    if registry is not None and bad_step_skip:
        registry.counter("train/steps_skipped")  # registered up front
    rollback_policy = (
        elastic.RollbackPolicy(
            nonfinite_steps=cfg.rollback_nonfinite_steps,
            loss_drift=cfg.rollback_loss_drift,
            drift_warmup=cfg.rollback_drift_warmup,
        )
        if cfg.bad_step_policy == "rollback"
        else None
    )
    rollbacks_done = 0
    lr_scale = 1.0
    last_saved_epoch = -1
    stopped_mid_epoch = False
    # Recomputed the way build_training computes schedule lengths, for the
    # rollback LR-backoff optimizer rebuild.
    total_steps = (
        global_step_count(len(train_manifest), host_batch, cfg.drop_remainder)
        * cfg.num_epochs
    )

    def _rollback_restore(at_epoch: int, at_step: int, reason: str):
        """--bad-step-policy rollback, the restore half: drain the async
        writer, restore the newest loadable checkpoint IN-PROCESS (the
        same elastic.restore_latest + placement dataflow as a process
        restart — minus the process death), optionally back off the LR,
        and return ``(next_epoch, next_start_step)`` from the restored
        cursor. Deterministic across hosts: the trigger reads globally-
        reduced values, so every process calls this at the same step."""
        nonlocal rollbacks_done, lr_scale, state, compiled_step, flops_per_step
        nonlocal last_saved_epoch, last_completed_epoch
        checkpointer.wait()
        if rollbacks_done >= cfg.max_rollbacks:
            metrics.write(
                {
                    "kind": "anomaly", "reason": "rollback_limit",
                    "epoch": at_epoch, "step": at_step,
                    "detail": f"{rollbacks_done} rollbacks hit "
                              f"max_rollbacks={cfg.max_rollbacks}",
                }
            )
            raise elastic.RollbackLimitError(
                f"bad-step rollback requested ({reason} at epoch {at_epoch} "
                f"step {at_step}) but {rollbacks_done} rollback(s) already "
                f"hit --max-rollbacks={cfg.max_rollbacks}; aborting — see "
                "the kind='rollback' trail in the metrics stream"
            )
        rollbacks_done += 1
        # Restore template with the UNSHARDED optimizer layout: a ZeRO
        # run's live [P, chunk] opt-state does not match the on-disk
        # gathered payload the checkpoint loader deserializes against.
        tmpl = state
        if opt_template is not None:
            tmpl = state.replace(
                opt_state=jax.tree_util.tree_map(
                    lambda s: np.zeros(s.shape, s.dtype), opt_template
                )
            )
        res = elastic.restore_latest(
            cfg.checkpoint_dir, tmpl, mesh, metrics=metrics, logger=logger,
            zero_shards_to=zero_shards_to,
        )
        if res is None:
            raise elastic.RollbackLimitError(
                f"bad-step rollback requested ({reason} at epoch {at_epoch} "
                f"step {at_step}) but no checkpoint exists in "
                f"{cfg.checkpoint_dir} to restore"
            )
        restored, ckpt_epoch, _ckpt_loss, info = res
        tx_changed = False
        if cfg.rollback_lr_backoff != 1.0:
            lr_scale *= cfg.rollback_lr_backoff
            restored = restored.replace(
                tx=make_optimizer(
                    cfg.learning_rate * lr_scale,
                    bundle.trainable_mask,
                    optimizer=cfg.optimizer,
                    lr_schedule=cfg.lr_schedule,
                    warmup_steps=cfg.warmup_steps,
                    total_steps=total_steps,
                    weight_decay=cfg.weight_decay,
                )
            )
            tx_changed = True
        # Re-place onto the mesh — the resume path's dataflow, including
        # the ZeRO detach (never device_put the full unsharded moments).
        raw_opt = restored.opt_state
        if defer_zero_opt:
            restored = restored.replace(opt_state=())
        placed = elastic.with_retries(
            lambda: elastic.checked_place(
                restored, mesh, zero_optimizer=cfg.zero_optimizer, fsdp=cfg.fsdp
            ),
            what="rollback state placement (device_put)",
            retries=cfg.resume_retries, backoff_s=cfg.resume_backoff_s,
            logger=logger,
        )
        if defer_zero_opt:
            placed = placed.replace(opt_state=zero_shard_opt_state(raw_opt, mesh))
        state = placed
        if tx_changed:
            # The LR lives inside the compiled step program: rebuild it
            # (one compile per backed-off rollback, documented cost).
            compiled_step, flops_per_step = build_compiled(state)
        rollback_policy.after_rollback()
        next_epoch = ckpt_epoch + 1
        # Epoch bookkeeping rewinds WITH the state: a later preemption save
        # must file under what the RESTORED state has completed, not what
        # the abandoned timeline had.
        last_completed_epoch = ckpt_epoch
        rb_cursor = (info.get("manifest") or {}).get("data_cursor")
        next_step, rb_why = validate_cursor(
            rb_cursor, cfg=cfg, fingerprint=fingerprint, n_steps=n_steps,
            start_epoch=next_epoch,
        )
        if rb_why is not None and (
            rb_cursor is not None or os.path.exists(info["path"] + ".dirty")
        ):
            # Same typed fallback contract as the resume path: ANY cursor
            # mismatch is recorded, never silently misaligned.
            metrics.write(
                {
                    "kind": "anomaly", "reason": "cursor_mismatch",
                    "epoch": next_epoch, "detail": rb_why,
                }
            )
            logger.warning(
                "rollback cursor unavailable (%s) — replaying epoch %d "
                "from step 0", rb_why, next_epoch,
            )
        metrics.write(
            {
                "kind": "rollback", "epoch": at_epoch, "step": at_step,
                "reason": reason, "restored_epoch": ckpt_epoch,
                "rollbacks": rollbacks_done, "lr_scale": round(lr_scale, 6),
                "path": info["path"],
            }
        )
        last_saved_epoch = ckpt_epoch
        logger.warning(
            "bad-step rollback #%d/%d (%s at epoch %d step %d): restored "
            "%s in-process, continuing at epoch %d step %d%s",
            rollbacks_done, cfg.max_rollbacks, reason, at_epoch, at_step,
            info["path"], next_epoch, next_step,
            f", LR scaled to {lr_scale:g}x" if tx_changed else "",
        )
        return next_epoch, next_step
    # A resumed run must not demote a better historical best (best.json
    # survives restarts; missing marker → any first accuracy wins). Only
    # process 0 reads the marker: on multi-host WITHOUT a shared checkpoint
    # dir the other processes would see no file and start from -inf, and a
    # diverged improvement decision gates a collective (checkpointer.save)
    # — a hang. Process 0's value is broadcast instead.
    best_accuracy = float("-inf")
    if cfg.track_best:
        _marker = (
            ckpt.best_marker(cfg.checkpoint_dir) if jax.process_index() == 0 else None
        )
        best_accuracy = _p0_scalar(
            _marker["accuracy"] if _marker else float("-inf"), mesh
        )
    # Epoch loop as an explicit cursor (epoch, next_start_step) rather than
    # a range: exact-step resume starts the first epoch mid-way, and a
    # bad-step rollback jumps BACKWARD to the restored checkpoint's cursor.
    epoch = start_epoch
    next_start_step = start_step
    last_completed_epoch = start_epoch - 1
    interrupted = None  # (epoch, next_step, steps_run_this_session) on mid-epoch stop
    with guard:
      try:
        while epoch < cfg.num_epochs:
            if _stop_agreed(watchdog.should_stop(epoch=epoch), mesh):
                summary.preempted = True
                logger.info(
                    "preemption signal: stopping before epoch %d "
                    "(progress saved; auto-resume continues from the latest "
                    "checkpoint)", epoch,
                )
                break
            start_step_this, next_start_step = next_start_step, 0
            t0 = time.perf_counter()  # ≙ MPI.Wtime() (main.py:145)
            health.start_epoch()  # re-arm the recompile counter per epoch
            heartbeat.start_epoch()  # beats never span epoch boundaries
            losses, counts = [], []
            loss_v = count_v = None  # [steps] device arrays, set below
            rollback_trigger = None  # (reason, step) breaking the step loop
            if cfg.device_cache and cfg.scan_epoch:
                # One dispatch for the whole epoch: stack the per-step index
                # batches and let the compiled lax.scan run every step
                # back-to-back on device. metrics come back as [n_steps]
                # arrays — used as-is, never split into per-step scalars.
                # (start_step_this is always 0 here: validate_cursor replays
                # rather than reshaping the compiled scan.)
                idx_steps = list(
                    cached_index_batches(cfg, n_cache, cache_batch, epoch, n_steps)
                )
                if idx_steps:  # zero-step epochs (tiny shard + drop_remainder) no-op
                    idx_all = np.stack([i for i, _ in idx_steps])
                    valid_all = np.stack([v for _, v in idx_steps])
                    with tracer.span("step", args={"epoch": epoch, "mode": "scan"}):
                        state, m = compiled_step(state, dataset, labels_all, idx_all, valid_all)
                        if telemetry_sync:
                            jax.block_until_ready(m["loss"])
                    loss_v, count_v = m["loss"], m["count"]
                    skipped_before_epoch = steps_skipped_total
                    if bad_step_skip and "skipped" in m:
                        # Mask skipped steps out of the epoch accounting (a
                        # discarded update contributes no samples, and its
                        # observed NaN loss must not poison the mean), and
                        # enforce the consecutive-skip budget post-hoc.
                        skip_v = np.asarray(m["skipped"], np.int64)
                        steps_skipped_total += int(skip_v.sum())
                        if registry is not None and skip_v.sum():
                            registry.counter("train/steps_skipped").inc(
                                int(skip_v.sum())
                            )
                        keep = jnp.asarray(1 - skip_v)
                        loss_v = jnp.where(keep == 1, loss_v, 0.0)
                        count_v = count_v * keep.astype(count_v.dtype)
                        # Seed from the previous epoch's trailing streak so
                        # a run of skips spanning the epoch boundary still
                        # trips the limit (the scan has no per-step host
                        # boundary to count at).
                        longest, run = 0, skip_streak
                        for flag in skip_v:
                            run = run + 1 if flag else 0
                            longest = max(longest, run)
                        skip_streak = run  # carries into the next epoch
                        if longest >= cfg.max_skipped_steps:
                            _abort_skip_limit(
                                metrics, epoch, int(longest), cfg.max_skipped_steps
                            )
                    # Per-step records post-hoc from the [n_steps] arrays
                    # (host timing is null — the scan never returns to the
                    # host between steps); sentinel checks every step.
                    health.on_scan_epoch(
                        epoch, m, steps_skipped_base=skipped_before_epoch
                    )
                    if cfg.log_every_steps:
                        for step_i in range(
                            cfg.log_every_steps - 1, int(loss_v.shape[0]), cfg.log_every_steps
                        ):
                            logger.info(
                                "epoch %d step %d loss %.4f",
                                epoch, step_i + 1, float(loss_v[step_i]),
                            )
                step_args = ()
            elif cfg.device_cache:
                # Same (seed, epoch) shuffle discipline as DataLoader.epoch, so
                # cached and streaming runs see identical batch compositions.
                step_args = (
                    (dataset, labels_all, idx, valid)
                    for idx, valid in cached_index_batches(
                        cfg, n_cache, cache_batch, epoch, n_steps,
                        start_step=start_step_this,
                    )
                )
            else:
                # Tail batches (drop_remainder=False) are padded to the static
                # shape with masked rows, so training keeps every image without
                # triggering an XLA recompile; device_prefetch keeps the H2D
                # copies a couple of steps ahead of compute.
                batches = synchronized_batches(
                    loader, epoch, n_steps, start_step=start_step_this
                )
                if faults.nonfinite_at_step:
                    batches = faults.poison_batches(batches, epoch)
                step_args = (
                    (dev_batch,)
                    for dev_batch in device_prefetch(
                        batches, mesh, host_batch, cfg.prefetch_device_batches,
                    )
                )
            stopped_mid_epoch = False
            step_iter = iter(step_args)
            step_i = start_step_this - 1
            while True:
                # Ingest span = time the consumer WAITS for the next batch:
                # decode + H2D dispatch not yet hidden by prefetch — the
                # host-side half of the data-wait vs device-compute split
                # the per-step records carry.
                t_ingest = time.perf_counter()
                with tracer.span("ingest"):
                    args = next(step_iter, None)
                if args is None:
                    break
                data_wait_s = time.perf_counter() - t_ingest
                step_i += 1
                # Single-process: stop promptly at a step boundary, dropping
                # the partial epoch (its updates stay in `state` but aren't
                # reported or saved as a completed epoch). Multi-host stops
                # only at the agreed epoch boundary above — a unilateral
                # mid-epoch break would strand the other hosts' collectives.
                if watchdog.should_stop(epoch=epoch, step=step_i) and jax.process_count() == 1:
                    stopped_mid_epoch = True
                    break
                t_step = time.perf_counter()
                with tracer.span("step", args={"epoch": epoch, "step": step_i}):
                    state, m = compiled_step(state, *args)
                    if telemetry_sync:
                        jax.block_until_ready(m["loss"])
                    # Inside the timed region so a faked straggler delay
                    # lands in the step time the heartbeat exchanges.
                    faults.maybe_delay()
                    # Slow-DCN-link fake (ISSUE 15): stretches only
                    # hierarchical steps — a flat mesh has no cross-pod
                    # phase to slow down.
                    faults.maybe_dcn_delay(_hier)
                step_s = time.perf_counter() - t_step
                was_skipped = None
                if bad_step_skip:
                    # The device already discarded the bad update; count the
                    # streak (the verdict is a psum'd value, so every host
                    # agrees) and mask the step out of the epoch accounting.
                    was_skipped = int(m["skipped"])
                    if was_skipped:
                        skip_streak += 1
                        steps_skipped_total += 1
                        if registry is not None:
                            registry.counter("train/steps_skipped").inc()
                        logger.warning(
                            "bad step skipped (non-finite update) at epoch "
                            "%d step %d — params unchanged, %d consecutive "
                            "(%d total)", epoch, step_i, skip_streak,
                            steps_skipped_total,
                        )
                        losses.append(jnp.zeros_like(m["loss"]))
                        counts.append(jnp.zeros_like(m["count"]))
                    else:
                        skip_streak = 0
                        losses.append(m["loss"])
                        counts.append(m["count"])
                else:
                    losses.append(m["loss"])
                    counts.append(m["count"])
                health.on_step(
                    epoch, step_i, m, data_wait_s, step_s,
                    skipped=was_skipped,
                    steps_skipped=steps_skipped_total if bad_step_skip else None,
                )
                heartbeat.on_step(epoch, step_i, step_s)
                if bad_step_skip and skip_streak >= cfg.max_skipped_steps:
                    _abort_skip_limit(
                        metrics, epoch, skip_streak, cfg.max_skipped_steps
                    )
                if rollback_policy is not None:
                    reason = rollback_policy.observe(
                        float(m["loss"]),
                        float(m["grad_norm"]) if "grad_norm" in m else None,
                    )
                    if reason is not None:
                        rollback_trigger = (reason, step_i)
                        break
                if registry is not None:
                    h_wait_ms.observe(data_wait_s * 1e3)
                    h_step_ms.observe(step_s * 1e3)
                    g_step_last.set(step_s * 1e3)
                if monitor is not None:
                    monitor.evaluate(epoch=epoch, step=step_i)
                if registry is not None and cfg.metrics_every_steps:
                    steps_since_snapshot += 1
                    if steps_since_snapshot % cfg.metrics_every_steps == 0:
                        metrics.write(
                            registry.snapshot_record(merge=snapshot_merge)
                        )
                faults.after_step(epoch, step_i)
                if cfg.log_every_steps and (step_i + 1) % cfg.log_every_steps == 0:
                    logger.info(
                        "epoch %d step %d loss %.4f", epoch, step_i + 1, float(m["loss"])
                    )
            if rollback_trigger is not None:
                # Bad-step rollback: restore the last good checkpoint
                # in-process and jump the epoch cursor back to it. The
                # partial epoch's bookkeeping (losses/counts) is discarded
                # with the poisoned state.
                reason, at_step = rollback_trigger
                epoch, next_start_step = _rollback_restore(epoch, at_step, reason)
                continue
            if stopped_mid_epoch:
                summary.preempted = True
                interrupted = (epoch, step_i, step_i - start_step_this, start_step_this)
                logger.info(
                    "preemption signal: stopping mid-epoch %d at step "
                    "boundary %d (partial-epoch state — saved dirty with an "
                    "exact-step data cursor; resume continues at step %d "
                    "when the cursor validates, replaying zero optimizer "
                    "steps)", epoch, step_i, step_i,
                )
                break
            # Device sync so the timer measures compute, not dispatch.
            jax.block_until_ready(state.params)
            dt = time.perf_counter() - t0
            if losses:  # per-step paths collected python lists
                loss_v = jnp.stack(losses)
                count_v = jnp.stack(counts)
            steps_run = int(loss_v.shape[0]) if loss_v is not None else 0
            if steps_run:
                # Per-sample accounting: weight each step's mean loss by its
                # global valid-row count, so padded tail steps aren't over-weighted
                # (matches the reference's per-sample loss bookkeeping) and
                # throughput never counts padding rows. One device sync per epoch.
                count_f = count_v.astype(jnp.float32)
                n_valid = float(jnp.sum(count_f))
                epoch_loss = (
                    float(jnp.sum(loss_v * count_f) / n_valid) if n_valid else float("nan")
                )
            else:
                n_valid = 0.0
                epoch_loss = float("nan")
            total_images += int(n_valid)
            ips = n_valid / dt if dt > 0 else 0.0
            # cost_analysis() FLOPs are PER-DEVICE under SPMD partitioning.
            per_chip_tflops = flops_per_step * steps_run / dt / 1e12 if dt > 0 else 0.0
            tflops = per_chip_tflops * jax.device_count()
            # mfu None (omitted) when either peak or FLOPs are unknown — a
            # confident "0.0%" would be indistinguishable from a stalled chip.
            mfu = 100.0 * per_chip_tflops / peak if (peak and flops_per_step > 0) else None
            # ≙ reference epoch log line (main.py:158-160), plus throughput/MFU
            logger.info(
                "Epoch: %d, Loss: %.6f, Time: %.2f s, %.1f img/s%s",
                epoch, epoch_loss, dt, ips,
                f", MFU {mfu:.1f}%" if mfu is not None else "",
            )
            metrics.write(
                {"kind": "epoch", "epoch": epoch, "loss": epoch_loss, "time_s": dt,
                 "images_per_sec": ips, "tflops": tflops, "mfu_pct": mfu}
            )
            if registry is not None:
                # The MFU-estimate / throughput gauges a fleet controller
                # (ROADMAP item 1) reads live instead of tailing the stream.
                # No monitor.evaluate here: rules are defined in per-step
                # evaluation units (for=/warmup/rate deltas), and a second
                # pass over the same last-step state would double-count a
                # single breach; the next epoch's first step evaluates
                # these gauges instead.
                registry.gauge("train/images_per_sec").set(ips)
                if mfu is not None:
                    registry.gauge("train/mfu_pct").set(mfu)
            if steps_run and n_valid:
                # Free epoch-granularity sentinel (the loss is already a
                # host float); zero-valid-row epochs are legitimately NaN.
                health.check_epoch(epoch, epoch_loss)
            summary.epoch_times.append(dt)
            summary.epoch_losses.append(epoch_loss)
            summary.epochs_run += 1

            if cfg.checkpoint_every_epochs and (epoch + 1) % cfg.checkpoint_every_epochs == 0:
                # Async: an on-device snapshot (~ms) releases the epoch loop
                # immediately; device_get + write happen on a background thread
                # (the sync version stalled epochs 25-45 s through the device
                # relay). ≙ rank-0 save (main.py:162-171), without stopping the
                # world. The topology sidecar carries the exact-step data
                # cursor: a clean epoch-E save resumes at (E+1, step 0).
                ckpt_t0 = time.perf_counter()
                with tracer.span("checkpoint", args={"epoch": epoch}):
                    path = checkpointer.save(
                        cfg.checkpoint_dir, epoch=epoch, state=_saveable(state),
                        loss=epoch_loss,
                        keep=cfg.keep_checkpoints,
                        moments_bf16=cfg.ckpt_bf16_moments,
                        manifest=dict(
                            topology,
                            data_cursor=data_cursor(
                                cfg, fingerprint, n_steps, epoch + 1, 0
                            ),
                        ),
                    )
                last_saved_epoch = epoch
                if path:
                    summary.checkpoint_path = path
                    logger.info(
                        "checkpoint dispatched: %s (%.2f s stall; ≙ main.py:162-171)",
                        path, time.perf_counter() - ckpt_t0,
                    )

            if cfg.validate:
                _val_span = tracer.begin("validate")
                try:
                    # Reference quirk preserved behind a flag: validation runs over the
                    # TRAIN manifest (main.py:104-112; SURVEY §3); val_on_train=False
                    # gives the honest test-split validation.
                    val_manifest = train_manifest if cfg.val_on_train else test_manifest
                    if cfg.device_cache and cfg.val_on_train:
                        # The cached train set IS the val set (main.py:104-112
                        # semantics): validate straight out of HBM.
                        acc, vloss = evaluate_cached(cfg, state, mesh, dataset, labels_all)
                    else:
                        if val_loader is None:
                            val_loader = make_eval_loader(
                                cfg, val_manifest, host_cache=cfg.host_cache
                            )
                        if (
                            cfg.host_cache
                            and cfg.val_on_train
                            and not val_loader._cache_complete
                        ):
                            # Same shard, same decode params: share the train
                            # loader's cache instead of decoding a second copy.
                            # Join the train loader's background backfill first —
                            # it finishes in bounded time, and adopting beats
                            # starting a duplicate full-shard decode.
                            loader.wait_cache_complete()
                            val_loader.adopt_cache(loader)
                        acc, vloss = evaluate_manifest(
                            cfg, state, mesh, val_manifest, loader=val_loader
                        )
                finally:
                    # finally: a crashed validation must still appear in the
                    # flushed trace as the span the run died in.
                    tracer.end(_val_span, args={"epoch": epoch})
                summary.val_accuracy = acc
                logger.info("Accuracy of the network: %.4f (val_on_train=%s)", acc, cfg.val_on_train)
                metrics.write({"kind": "val", "epoch": epoch, "accuracy": acc, "loss": vloss})

                if cfg.track_best and acc > best_accuracy:
                    # acc is globally reduced, so every process agrees on the
                    # improvement; any save below is a global snapshot every
                    # process must run (only process 0 writes files/markers).
                    # The marker is published strictly AFTER the checkpoint
                    # file is durable — a crash mid-write must never leave
                    # best.json naming a file that doesn't exist.
                    best_accuracy = acc
                    summary.best_accuracy = acc

                    def _mark_best(ckpt_path, *, _epoch=epoch, _acc=acc):
                        ckpt.write_best_marker(
                            cfg.checkpoint_dir, epoch=_epoch, accuracy=_acc,
                            ckpt_path=ckpt_path,
                        )

                    if last_saved_epoch == epoch:
                        # This epoch's periodic save is already in flight:
                        # join it (bounded — validation usually outlasts the
                        # write anyway), then mark. `path` is that save's
                        # return (this epoch's file on process 0).
                        checkpointer.wait()
                        _mark_best(path)
                    else:
                        best_path = checkpointer.save(
                            cfg.checkpoint_dir, epoch=epoch, state=_saveable(state),
                            loss=epoch_loss, keep=cfg.keep_checkpoints,
                            on_durable=_mark_best,
                            moments_bf16=cfg.ckpt_bf16_moments,
                            manifest=dict(
                                topology,
                                data_cursor=data_cursor(
                                    cfg, fingerprint, n_steps, epoch + 1, 0
                                ),
                            ),
                        )
                        last_saved_epoch = epoch
                        if best_path:
                            summary.checkpoint_path = best_path
                    logger.info("new best: val acc %.4f at epoch %d", acc, epoch)

            last_completed_epoch = epoch
            epoch += 1

      except BaseException:
        # Drain the in-flight write on the failure path too, but never let a
        # secondary writer error replace the primary exception the user
        # needs to see. (The trace flush on failure lives in train()'s
        # outer handler, which also covers build/compile-time crashes.)
        try:
            checkpointer.wait()
        except BaseException as werr:
            logger.warning("background checkpoint write also failed: %s", werr)
        raise
      if summary.preempted and cfg.checkpoint_every_epochs:
        # Preserve whatever the preemption would otherwise lose. Two cases:
        #
        # - Stopped MID-epoch with steps run this session: save the state
        #   (which carries the partial epoch's updates) DIRTY under the
        #   last completed epoch, with the exact-step data cursor in the
        #   topology sidecar — resume continues at step N+1, replaying
        #   ZERO optimizer steps (ISSUE 10). A run that stopped before
        #   running any new step saves nothing new (the on-disk checkpoint
        #   already describes this state); mid-epoch-0 stops with no
        #   completed epoch still have no epoch to file under, so the
        #   partial steps are dropped exactly as before.
        # - Stopped at an epoch boundary: save completed-but-unsaved
        #   epochs (checkpoint_every_epochs > 1 leaves up to k-1 unsaved).
        completed = last_completed_epoch
        # Never rewrite the best-pinned checkpoint with partial-epoch state:
        # best.json claims that file holds the accuracy it measured, and the
        # dirty save below files under `completed` — the same name as that
        # epoch's clean save. Integrity of the pinned file outranks keeping
        # the partial steps (they are dropped, exactly the old behavior).
        _best = ckpt.best_marker(cfg.checkpoint_dir) if cfg.track_best else None
        _best_is_target = bool(
            _best
            and completed >= 0
            and _best.get("checkpoint")
            == os.path.basename(ckpt._ckpt_path(cfg.checkpoint_dir, completed))
        )
        if (
            interrupted is not None and interrupted[2] > 0 and completed >= 0
            and not _best_is_target
        ):
            int_epoch, int_step, _steps, _start = interrupted
            path = checkpointer.save(
                cfg.checkpoint_dir, epoch=completed, state=_saveable(state),
                loss=epoch_loss,
                keep=cfg.keep_checkpoints, dirty=True,
                moments_bf16=cfg.ckpt_bf16_moments,
                manifest=dict(
                    topology,
                    data_cursor=data_cursor(
                        cfg, fingerprint, n_steps, int_epoch, int_step
                    ),
                ),
            )
            last_saved_epoch = completed
            if path:
                summary.checkpoint_path = path
                logger.info(
                    "preemption checkpoint dispatched: %s (dirty; cursor "
                    "epoch %d step %d)", path, int_epoch, int_step,
                )
        elif (
            completed >= start_epoch
            and completed != last_saved_epoch
            # A stop with ZERO new steps in the interrupted epoch is a clean
            # boundary state ONLY if the epoch wasn't entered mid-way (a
            # resumed-then-immediately-stopped run's state is the on-disk
            # dirty checkpoint, already saved).
            and (interrupted is None or interrupted[3] == 0)
        ):
            path = checkpointer.save(
                cfg.checkpoint_dir, epoch=completed, state=_saveable(state),
                loss=epoch_loss,
                keep=cfg.keep_checkpoints,
                moments_bf16=cfg.ckpt_bf16_moments,
                manifest=dict(
                    topology,
                    data_cursor=data_cursor(
                        cfg, fingerprint, n_steps, completed + 1, 0
                    ),
                ),
            )
            if path:
                summary.checkpoint_path = path
                logger.info("preemption checkpoint dispatched: %s", path)

      # Clean path: the last dispatched write must land before callers read
      # the file (resume, evaluate), and a writer error must fail the run
      # loudly. Still under the guard: see the note at `with guard:` above.
      checkpointer.wait()

    if profiling:
        jax.profiler.stop_trace()
        logger.info("profiler trace written to %s", cfg.profile_dir)

    wall = time.perf_counter() - train_t0
    summary.final_loss = epoch_loss
    summary.images_per_sec = total_images / wall if wall > 0 else 0.0
    trace_out = tracer.close()
    if trace_out:
        logger.info("host trace spans written to %s (chrome://tracing)", trace_out)
    if registry is not None:
        # Final snapshot so even a run below the step cadence leaves one
        # kind="metrics" record (all processes reach here together — the
        # epoch loop breaks by agreement — so the merge collective is safe).
        metrics.write(registry.snapshot_record(merge=snapshot_merge))
    metrics.close()
    if flight is not None:
        flight.close()
    return summary


def main(argv=None) -> TrainSummary:
    from mpi_pytorch_tpu.config import parse_config

    cfg = parse_config(argv)
    return train(cfg)


if __name__ == "__main__":
    main()
