"""Input/execution-mode benchmark: the round-2 feeding features on a chip.

VERDICT r2 flagged that uint8 feeding, the device cache, and scan-epoch had
only virtual-CPU-mesh verification. This sweeps the HEADLINE workload
(resnet18, 64 500 classes, 128px) through each mode with the same timing
discipline as bench.py/bench_zoo.py and prints one JSON line per mode:

    stream-f32    — host batches as float32 (reference-parity numerics)
    stream-bf16   — host batches as bfloat16 (half the H2D bytes)
    stream-uint8  — raw pixels + on-device normalize (1/4 the H2D bytes)
    cached        — HBM-resident dataset, per-step index gather
    cached-scan   — HBM-resident dataset, whole epoch as one lax.scan

Streaming modes re-shard a fresh host batch EVERY step (device_put inside
the timed loop), so they carry the real H2D cost the dtype modes differ by;
the cached modes send only [B] int32 indices (and the scan, one dispatch per
epoch). Run: ``python tools/bench_modes.py [--steps 20] [--out path]``.
The packed-mmap path is host-side decode (no chip leg) — its numbers live in
docs/RESULTS.md §4 host-ingest table.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMG_PER_SEC_PER_WORKER = 4.4  # BASELINE.md
MODEL, NUM_CLASSES, IMAGE = "resnet18", 64500, 128
CACHE_ROWS = 8192  # HBM-resident rows for the cached modes (~400 MB f32)


def _setup():
    """Identical model/state for every mode — the dtype distinction lives
    entirely in the host batch (`_host_batch`) and the ingest cast."""
    import optax  # noqa: F401  (state factory pulls it in)

    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.models import create_model_bundle
    from mpi_pytorch_tpu.parallel.mesh import create_mesh
    from mpi_pytorch_tpu.train.state import TrainState, make_optimizer
    from mpi_pytorch_tpu.train.step import place_state_on_mesh

    mesh = create_mesh(Config().mesh)
    bundle, variables = create_model_bundle(
        MODEL, NUM_CLASSES, rng=jax.random.PRNGKey(0), image_size=IMAGE,
        dtype=jnp.bfloat16, param_dtype=jnp.float32,
    )
    state = TrainState.create(
        apply_fn=bundle.model.apply, variables=variables,
        tx=make_optimizer(4e-4), rng=jax.random.PRNGKey(1),
    )
    return mesh, place_state_on_mesh(state, mesh)


def _host_batch(batch: int, input_dtype: str):
    rng = np.random.default_rng(0)
    labels = rng.integers(0, NUM_CLASSES, size=(batch,)).astype(np.int32)
    if input_dtype == "uint8":
        images = rng.integers(0, 256, size=(batch, IMAGE, IMAGE, 3)).astype(np.uint8)
    else:
        images = rng.standard_normal((batch, IMAGE, IMAGE, 3)).astype(np.float32)
        if input_dtype == "bfloat16":
            images = images.astype(jnp.bfloat16)
    return images, labels


def bench_streaming(input_dtype: str, batch_per_chip: int, steps: int, warmup: int):
    from mpi_pytorch_tpu.parallel.mesh import shard_batch
    from mpi_pytorch_tpu.train.step import make_train_step

    mesh, state = _setup()
    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    images, labels = _host_batch(batch, input_dtype)
    step = make_train_step(jnp.bfloat16)
    compiled = step.lower(state, shard_batch((images, labels), mesh)).compile()

    for _ in range(warmup):
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for _ in range(steps):
        # The device_put is INSIDE the timed loop on purpose: the H2D
        # transfer is the thing the input dtypes differ by.
        state, _ = compiled(state, shard_batch((images, labels), mesh))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    return dt, steps * batch, n_chips


def bench_cached(scan: bool, batch_per_chip: int, steps: int, warmup: int):
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpi_pytorch_tpu.train.step import (
        make_cached_train_step,
        make_scanned_epoch,
    )

    mesh, state = _setup()
    n_chips = jax.device_count()
    batch = batch_per_chip * n_chips
    n_data = mesh.shape[mesh.axis_names[0]]
    rows = -(-CACHE_ROWS // n_data) * n_data
    rng = np.random.default_rng(0)
    dataset = jax.device_put(
        rng.standard_normal((rows, IMAGE, IMAGE, 3)).astype(np.float32),
        NamedSharding(mesh, P(mesh.axis_names[0])),
    )
    labels_all = jax.device_put(
        rng.integers(0, NUM_CLASSES, size=(rows,)).astype(np.int32),
        NamedSharding(mesh, P()),
    )
    idx = rng.integers(0, rows, size=(steps + warmup, batch)).astype(np.int32)
    valid = np.ones((steps + warmup, batch), bool)

    if scan:
        epoch_fn = make_scanned_epoch(mesh, jnp.bfloat16)
        compiled = epoch_fn.lower(
            state, dataset, labels_all, idx[:steps], valid[:steps]
        ).compile()
        state, _ = compiled(state, dataset, labels_all, idx[:steps], valid[:steps])
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        state, _ = compiled(state, dataset, labels_all, idx[:steps], valid[:steps])
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        return dt, steps * batch, n_chips

    step = make_cached_train_step(mesh, jnp.bfloat16)
    compiled = step.lower(state, dataset, labels_all, idx[0], valid[0]).compile()
    for i in range(warmup):
        state, _ = compiled(state, dataset, labels_all, idx[i], valid[i])
    jax.block_until_ready(state.params)
    t0 = time.perf_counter()
    for i in range(steps):
        state, _ = compiled(state, dataset, labels_all, idx[warmup + i], valid[warmup + i])
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    return dt, steps * batch, n_chips


MODES = {
    "stream-f32": lambda b, s, w: bench_streaming("float32", b, s, w),
    "stream-bf16": lambda b, s, w: bench_streaming("bfloat16", b, s, w),
    "stream-uint8": lambda b, s, w: bench_streaming("uint8", b, s, w),
    "cached": lambda b, s, w: bench_cached(False, b, s, w),
    "cached-scan": lambda b, s, w: bench_cached(True, b, s, w),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--batch", type=int, default=2048, help="per chip")
    ap.add_argument("--modes", default=",".join(MODES))
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    records = []
    for mode in (m.strip() for m in args.modes.split(",") if m.strip()):
        try:
            dt, images, n_chips = MODES[mode](args.batch, args.steps, args.warmup)
            rec = {
                "mode": mode,
                "batch_per_chip": args.batch,
                "images_per_sec_per_chip": round(images / dt / n_chips, 1),
                "vs_baseline": round(
                    images / dt / n_chips / REFERENCE_IMG_PER_SEC_PER_WORKER, 1
                ),
            }
        except Exception as e:
            rec = {"mode": mode, "error": f"{type(e).__name__}: {e}"[:300]}
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
