"""Multi-host step-time heartbeat with straggler flagging (obs tentpole
part 3).

On a pod, every step is a collective: ONE slow host sets the pace for all
of them, and from process 0's per-epoch numbers a straggler is invisible —
the epoch is just "slow". The heartbeat makes it visible: every N steps all
processes exchange their mean step time over the collectives path
(``parallel/collectives.host_allgather`` — tiny f32 rows, not tensors), and
process 0's metrics stream gains a ``kind="heartbeat"`` record with the
per-host rows plus the indices of any host slower than
``straggler_threshold × median``.

The exchange is itself a collective, so it must run at the SAME step on
every process — the trainer guarantees that (``global_step_count`` syncs the
loop), and the heartbeat only counts steps, never decides per-host.
Single-process runs degrade gracefully: one row, never a straggler.
"""

from __future__ import annotations

import numpy as np

# Registry gauges the heartbeat publishes — ONLY when --heartbeat-every-
# steps > 0. config.validate_config imports this set to reject SLO rules
# over these names when no beat will ever publish them (the health.py
# STEP_GAUGES discipline).
BEAT_GAUGES = (
    "train/straggler_streak",
    "train/median_step_ms",
    "train/slowest_step_ms",
    "train/heartbeat_images_per_sec",
)


def flag_stragglers(per_host_ms, threshold: float) -> list[int]:
    """Indices (= process ids) of hosts slower than ``threshold × median``.
    Pure so the policy is unit-testable with a faked slow host; a non-finite
    or zero median flags nothing (no baseline to be slow against)."""
    a = np.asarray(per_host_ms, np.float64)
    if a.size < 2:
        return []
    med = float(np.median(a))
    if not np.isfinite(med) or med <= 0:
        return []
    return [int(i) for i in np.nonzero(a > threshold * med)[0]]


class Heartbeat:
    """Periodic per-host step-time aggregation into the metrics stream."""

    def __init__(
        self,
        metrics,
        *,
        every_steps: int = 0,
        threshold: float = 1.5,
        batch_images: int = 0,
        tracer=None,
        gather=None,
        registry=None,
    ):
        self.metrics = metrics
        self.every = int(every_steps)
        self.enabled = self.every > 0
        self.threshold = float(threshold)
        self.batch_images = int(batch_images)
        self.tracer = tracer
        # Live-telemetry publication (obs/metrics.MetricsRegistry): per-beat
        # straggler/pace gauges the SLO monitor's fleet rules read —
        # pre-bound, and registered up front so every host's registry has
        # the identical name set (the cross-host merge flattens by it).
        self.registry = registry
        if registry is not None:
            self._g_streak = registry.gauge("train/straggler_streak")
            self._g_median = registry.gauge("train/median_step_ms")
            self._g_slowest = registry.gauge("train/slowest_step_ms")
            self._g_ips = (
                registry.gauge("train/heartbeat_images_per_sec")
                if self.batch_images else None
            )
        if gather is None:
            from mpi_pytorch_tpu.parallel.collectives import host_allgather

            gather = host_allgather
        self._gather = gather
        self._window: list[float] = []
        # Consecutive beats that flagged at least one straggler — the
        # persistent-slow-host signal the preemption watchdog
        # (train/elastic.py) can preempt on. Deliberately NOT reset per
        # epoch: a real straggler outlives epoch boundaries, and the
        # beats that feed it already never span one.
        self.straggler_streak = 0

    def start_epoch(self) -> None:
        """Drop samples left over when an epoch's step count is not a
        multiple of ``every`` (or a preemption broke the loop early) — a
        beat must never average step times across epoch boundaries, where
        compile/warmup skew from the previous epoch's tail would pollute
        the per-host rows every process feeds the straggler median."""
        self._window.clear()

    def on_step(self, epoch: int, step: int, step_s: float) -> None:
        """Accumulate this step's wall time; every ``every`` steps, exchange
        and record. All processes call this at every step (the exchange is
        a collective), and every process computes the same flags — only
        process 0's MetricsWriter actually writes."""
        if not self.enabled:
            return
        self._window.append(step_s)
        if (step + 1) % self.every != 0:
            return
        local_ms = 1e3 * sum(self._window) / len(self._window)
        self._window.clear()
        per_host = np.asarray(self._gather(np.asarray([local_ms], np.float32)))
        per_host_ms = [round(float(v), 3) for v in per_host[:, 0]]
        stragglers = flag_stragglers(per_host_ms, self.threshold)
        self.straggler_streak = self.straggler_streak + 1 if stragglers else 0
        record = {
            "kind": "heartbeat",
            "epoch": epoch,
            "step": step,
            "step_ms": per_host_ms,
            "median_step_ms": round(float(np.median(per_host_ms)), 3),
            "stragglers": stragglers,
            "threshold": self.threshold,
        }
        if self.batch_images:
            # Steps are collective, so the GLOBAL pace is set by the slowest
            # host — that is the throughput the run actually achieves.
            record["images_per_sec"] = round(
                self.batch_images / (max(per_host_ms) / 1e3), 1
            )
        self.metrics.write(record)
        if self.registry is not None:
            self._g_streak.set(self.straggler_streak)
            self._g_median.set(record["median_step_ms"])
            self._g_slowest.set(max(per_host_ms))
            if self._g_ips is not None and "images_per_sec" in record:
                self._g_ips.set(record["images_per_sec"])
        if self.tracer is not None:
            self.tracer.instant(
                "heartbeat", args={"step": step, "stragglers": stragglers}
            )
        if stragglers:
            from mpi_pytorch_tpu.utils.logging import run_logger

            run_logger().warning(
                "straggler host(s) %s: step time %s ms vs median %.1f ms "
                "(threshold %.2fx) at epoch %d step %d",
                stragglers, [per_host_ms[i] for i in stragglers],
                float(np.median(per_host_ms)), self.threshold, epoch, step,
            )
