"""Attention microbench: full (materialized S×S) vs flash (Pallas) on chip.

The flash kernel's win grows with sequence length — this sweeps S and
prints one JSON line per (impl, S) for fwd+bwd through a jitted
grad step, plus the peak-memory story XLA reports:

    python tools/bench_attention.py [--seqs 512,1024,2048,4096] [--out f]

On non-TPU backends the flash path falls back to full attention
(ops/flash_attention.py gating), so chip runs are the meaningful ones;
the battery stages this after the zoo sweep.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

B, H, D = 4, 6, 64  # vit_s16-shaped heads


def bench_one(impl: str, seq: int, steps: int, warmup: int) -> dict:
    from mpi_pytorch_tpu.ops.flash_attention import flash_attention
    from mpi_pytorch_tpu.ops.ring_attention import full_attention

    fn = {
        "full": lambda q, k, v: full_attention(q, k, v),
        "flash": lambda q, k, v: flash_attention(q, k, v),
    }[impl]

    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.standard_normal((B, seq, H, D)), jnp.bfloat16
    )
    q, k, v = mk(), mk(), mk()

    # The inputs are DONATED and each step consumes the previous step's
    # outputs (a true dependency chain), and the timing barrier is a VALUE
    # FETCH of a scalar computed from the final state — measured live on
    # this relay: ``block_until_ready`` returns in ~0.03 ms/step while the
    # actual chained work takes ~170 ms/step (the relay acks readiness
    # without execution). A fetched value cannot be fabricated, so the
    # fetch is the only trustworthy barrier for short programs; its one
    # round-trip is amortized over ``steps``.
    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(q, k, v):
        def loss(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_).astype(jnp.float32) ** 2)

        _, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        eps = jnp.asarray(1e-3, q.dtype)  # tiny axpy: negligible vs attention
        return q - eps * grads[0], k - eps * grads[1], v - eps * grads[2]

    compiled = step.lower(q, k, v).compile()
    mem = None
    try:
        ma = compiled.memory_analysis()
        mem = int(getattr(ma, "temp_size_in_bytes", 0))
    except Exception:
        pass

    def sync(x):  # true execution barrier (see note above)
        return float(jnp.sum(x.astype(jnp.float32)))

    for _ in range(warmup):
        q, k, v = compiled(q, k, v)
    sync(q)
    t0 = time.perf_counter()
    for _ in range(steps):
        q, k, v = compiled(q, k, v)
    sync(q)
    dt = (time.perf_counter() - t0) / steps

    rec = {
        "impl": impl, "seq": seq, "batch": B, "heads": H, "head_dim": D,
        "fwd_bwd_ms": round(dt * 1e3, 3),
    }
    if mem is not None:
        rec["temp_hbm_mb"] = round(mem / 1e6, 1)
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="512,1024,2048,4096")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    records = []
    for seq in (int(s) for s in args.seqs.split(",") if s):
        for impl in ("full", "flash"):
            try:
                rec = bench_one(impl, seq, args.steps, args.warmup)
            except Exception as e:
                rec = {"impl": impl, "seq": seq,
                       "error": f"{type(e).__name__}: {e}"[:300]}
            records.append(rec)
            print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
