"""Per-op roofline of a compiled train step — the MFU-ceiling instrument.

VERDICT r2 asked either for ≥55% MFU or a committed proof of the physical
ceiling. This tool supplies the instrument: it compiles a model's train step,
walks the OPTIMIZED HLO's entry computation, and for every executed
instruction estimates

- ``bytes``: HBM traffic = operand sizes + output size (fusion parameters
  are real HBM reads and the fusion output a real HBM write, so
  instruction-level accounting is the right granularity after XLA fusion)
  — EXCLUDING buffers pinned on-chip (``S(n)`` memory-space layouts),
  alias-only ops (``*-done``, ``ConcatBitcast``, ``bitcast``), and the
  operand-alias element of ``*-start`` tuples;
- ``flops``: per-axis valid-MAC counting for ``convolution``
  (2 · out_batch·out_feat · Π_axis valid (o,k) pairs · rhs_input_feature —
  padding/striding/dilation positions excluded; valid for forward, grad-x,
  and grad-w convs alike, and window-less head-matmul convolutions score
  as the dots they are) and ``dot`` (2 · M·N·K), 0 for data movement and
  elementwise work (their cost is the bytes);
- ``attainable_ms``: max(flops / peak_FLOPs, bytes / peak_BW) — the roofline
  lower bound for that op on this chip.

Σ attainable_ms over the step is a LOWER BOUND on the step time a perfect
scheduler could reach, so ``model_flops / (peak · Σ attainable)`` is the
MFU ceiling the memory system permits for this HLO — if that ceiling is
near the measured MFU, the gap to 55% is physics (bandwidth-bound ops),
not an unhunted flag.

    python tools/roofline.py --model resnet18 --batch 2048 [--top 20]
    python tools/roofline.py --model densenet121 --batch 1024 --json out.json

Caveats (estimate, not a profile): while-loop bodies (the scanned-epoch
mode) are NOT expanded — roofline the per-step program, which is the scan
body (trainer FLOPs accounting relies on the same identity); intra-fusion
recompute is invisible; CPU runs print bytes/flops but no attainable column
(no peak numbers for CPU).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# Shape + its layout braces, e.g. bf16[1024,64,64,96]{0,3,2,1:T(8,128)(2,1)S(1)}
_SHAPE_LAYOUT_RE = re.compile(r"(\w+)\[([\d,]*)\](\{[^{}]*\})?")


def _bytes_of(shape_text: str, hbm_only: bool) -> int:
    total = 0
    for dtype, dims, layout in _SHAPE_LAYOUT_RE.findall(shape_text):
        if dtype not in _DTYPE_BYTES:
            continue
        if hbm_only and layout and "S(" in layout:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def shape_bytes(shape_text: str) -> int:
    """Total bytes of an HLO shape string (tuples: sum of elements)."""
    return _bytes_of(shape_text, hbm_only=False)


def shape_hbm_bytes(shape_text: str) -> int:
    """Bytes of an HLO shape that actually live in HBM.

    A layout with an ``S(n)`` memory-space annotation is NOT in HBM
    (on TPU, space 1 = VMEM, 2 = SMEM, 6 = sync flags): XLA pins those
    inter-kernel buffers on-chip, so their reads/writes consume zero HBM
    bandwidth. Counting them as HBM traffic pushed mobilenet_v2's
    Σ attainable above its *measured* step time — an impossible "lower
    bound"."""
    return _bytes_of(shape_text, hbm_only=True)


def _shape_dims(shape_text: str):
    m = _SHAPE_RE.search(shape_text)
    if not m:
        return None, []
    dtype, dims = m.groups()
    return dtype, [int(d) for d in dims.split(",") if d]


_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\([^)]*\)|[\w\[\],{}:()\d\s]*?)\s+"
    r"([\w\-]+)\((.*)$"
)


# Computation header: `%name (params...) -> result {` — greedy `.*` spans
# tuple-typed parameter lists (inner parens), which a lazy `[^)]*` would not.
_COMP_HEAD_RE = re.compile(r"^%?([\w.\-]+)\s+\(.*\)\s*->.*\{")


def parse_computations(hlo_text: str):
    """{computation_name: [(name, shape_text, op, rest), ...]} for every
    computation block (tuple-typed parameters included); the ENTRY
    computation is keyed "ENTRY"."""
    comps: dict[str, list] = {}
    current = None
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY "):
            current = "ENTRY"
            comps[current] = []
            continue
        if current is None:
            if not line.startswith((" ", "}")):  # headers only at col 0
                m_head = _COMP_HEAD_RE.match(line)
                if m_head:
                    current = m_head.group(1)
                    comps[current] = []
            continue
        if line.startswith("}"):
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m:
            comps[current].append(
                (m.group(1), m.group(2), m.group(3), m.group(4))
            )
    return comps


def _comp_flops(instrs) -> float:
    """Σ dot/conv FLOPs inside one (fused) computation."""
    shapes = {name: shape for name, shape, _, _ in instrs}
    total = 0.0
    for _, shape_text, op, rest in instrs:
        if op == "convolution":
            total += conv_flops(shape_text, rest, shapes)
        elif op == "dot":
            total += dot_flops(shape_text, rest, shapes)
    return total


def _parse_window(rest: str):
    """window={size=.. stride=.. pad=.. lhs_dilate=.. rhs_dilate=..} →
    (sizes, strides, pads_lo, lhs_dil, rhs_dil) per spatial axis."""
    m = re.search(r"window=\{([^}]*)\}", rest)
    if not m:
        # 0-spatial-dim convs (XLA canonicalizes the head matmul into
        # `convolution ... dim_labels=bf_io->bf` with no window attribute):
        # zero axes → the formula degenerates to 2·out_numel·rhs_i, the
        # exact dot count.
        return [], [], [], [], []
    body = m.group(1)
    mk = re.search(r"size=([\dx]+)", body)
    if not mk:
        # A window={...} attribute with no size= key carries no spatial
        # extent — treat it exactly like a missing window (zero spatial
        # axes → the dot-degenerate count), NOT as unparseable: returning
        # None here would zero the conv's FLOPs, contradicting the
        # "never return 0 for a conv we can see" stance below.
        return [], [], [], [], []
    sizes = [int(x) for x in mk.group(1).split("x")]
    n = len(sizes)

    def vec(key, default):
        mv = re.search(rf"{key}=([\dx]+)", body)
        if not mv:
            return [default] * n
        return [int(x) for x in mv.group(1).split("x")]

    strides = vec("stride", 1)
    lhs_dil = vec("lhs_dilate", 1)
    rhs_dil = vec("rhs_dilate", 1)
    mp = re.search(r"pad=([\d_x\-]+)", body)
    if mp:
        pads_lo = [int(x.split("_")[0]) for x in mp.group(1).split("x")]
    else:
        pads_lo = [0] * n
    return sizes, strides, pads_lo, lhs_dil, rhs_dil


def _axis_macs(out_size, lhs_size, window, stride, pad_lo, lhs_d, rhs_d):
    """Valid (output-position, window-element) pairs along one spatial axis.

    A window element k at output position o reads base-input coordinate
    j = o·stride + k·rhs_dilate − pad_lo, which holds real data only when
    0 ≤ j ≤ (lhs_size−1)·lhs_dilate and j is a multiple of lhs_dilate —
    everything else is padding/dilation zeros a real implementation skips.
    Counting only those pairs keeps Σ attainable a true LOWER bound."""
    total = 0
    ext = (lhs_size - 1) * lhs_d
    for k in range(window):
        base = k * rhs_d - pad_lo
        lo = max(0, math.ceil(-base / stride))
        hi = min(out_size - 1, math.floor((ext - base) / stride))
        if hi < lo:
            continue
        if lhs_d == 1:
            total += hi - lo + 1
        else:
            total += sum(
                1 for o in range(lo, hi + 1) if (o * stride + base) % lhs_d == 0
            )
    return total


def conv_flops(shape_text: str, rest: str, shapes: dict) -> float:
    """2 · out_batch·out_feat · Π_axis valid_MACs(axis) · rhs_input_feature.

    Two refinements over naive 2·out_numel·window_numel·rhs_i, both needed
    for the count to stay a valid roofline LOWER bound on executed work:

    - window/dim_labels come from the instruction itself, NOT from assuming
      the rhs is a (kh,kw,Ci,Co) kernel: in backward convs the rhs is an
      activation tensor and the window spans the whole image (a densenet
      grad-w conv was attributed ~2.0e15 FLOPs, ~30x its true cost, by the
      old kernel-shaped heuristic).
    - padding/dilation positions are EXCLUDED per axis (``_axis_macs``).
      XLA canonicalizes the grad-x of a 1×1 conv into a 64×64-window conv
      over the 63-padded weight — 4095 of 4096 window positions hit
      padding, so the naive count was 4096× too high (mobilenet_v2's
      "52.8 TFLOP" fusion is really 12.9 GFLOP).

    Grouped convs need no special case: the HLO rhs input-feature dim is
    already Cin/groups."""
    _, out_dims = _shape_dims(shape_text)
    ops = re.findall(r"%([\w.\-]+)", rest.split("),")[0])
    if len(ops) < 2 or not out_dims:
        return 0.0
    win = _parse_window(rest)
    ml = re.search(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)", rest)
    _, lhs_dims = _shape_dims(shapes.get(ops[0], ""))
    _, rhs_dims = _shape_dims(shapes.get(ops[1], ""))
    if not (win and ml and rhs_dims):
        return 0.0
    sizes, strides, pads_lo, lhs_dil, rhs_dil = win
    lhs_labels, rhs_labels, out_labels = ml.groups()
    i_idx = rhs_labels.find("i")
    if i_idx < 0 or i_idx >= len(rhs_dims):
        return 0.0

    out_numel = 1
    for d in out_dims:
        out_numel *= d
    naive = 2.0 * out_numel * math.prod(sizes) * rhs_dims[i_idx]

    # Per-axis valid-MAC refinement; fall back to the naive count when the
    # label→dim mapping doesn't resolve (defensive: never return 0 for a
    # conv we can see).
    bf_numel = 1.0
    for label, d in zip(out_labels, out_dims):
        if label in ("b", "f"):
            bf_numel *= d
    macs = 1.0
    for axis, w in enumerate(sizes):
        a = str(axis)
        o_idx, l_idx = out_labels.find(a), lhs_labels.find(a)
        if o_idx < 0 or l_idx < 0 or o_idx >= len(out_dims) or l_idx >= len(
            lhs_dims or []
        ):
            return naive
        macs *= _axis_macs(
            out_dims[o_idx], lhs_dims[l_idx], w,
            strides[axis], pads_lo[axis], lhs_dil[axis], rhs_dil[axis],
        )
    return min(naive, 2.0 * bf_numel * macs * rhs_dims[i_idx])


def dot_flops(shape_text: str, rest: str, shapes: dict) -> float:
    """2 · M·N·K: out_numel × K (contracting size from operand 0)."""
    _, out_dims = _shape_dims(shape_text)
    ops = re.findall(r"%([\w.\-]+)", rest.split("),")[0])
    if not ops or not out_dims:
        return 0.0
    _, a_dims = _shape_dims(shapes.get(ops[0], ""))
    mc = re.search(r"lhs_contracting_dims=\{([\d,]+)\}", rest)
    if not a_dims or not mc:
        return 0.0
    k = 1
    for i in (int(x) for x in mc.group(1).split(",")):
        if i < len(a_dims):
            k *= a_dims[i]
    out_numel = 1
    for d in out_dims:
        out_numel *= d
    return 2.0 * out_numel * k


def roofline(hlo_text: str, peak_tflops: float | None, peak_gbps: float | None):
    """Per-instruction roofline rows for the entry computation."""
    comps = parse_computations(hlo_text)
    instrs = comps.get("ENTRY", [])
    shapes = {name: shape for name, shape, _, _ in instrs}
    # FLOPs of dots/convs INSIDE each fused computation, attributed to the
    # calling fusion instruction (XLA sometimes fuses the conv/dot itself).
    fused_flops = {
        cname: _comp_flops(cinstrs)
        for cname, cinstrs in comps.items()
        if cname != "ENTRY"
    }

    rows = []
    for name, shape_text, op, rest in instrs:
        if op in ("parameter", "constant", "tuple", "get-tuple-element", "bitcast"):
            continue
        # *-done ops alias the transfer their *-start already counted;
        # ConcatBitcast stitches async slice DMAs together by aliasing —
        # neither moves a byte of its own.
        if op.endswith("-done") or "ConcatBitcast" in rest:
            continue
        out_b = shape_hbm_bytes(shape_text)
        operand_names = re.findall(r"%([\w.\-]+)", rest.split(", kind=")[0])
        in_b = sum(shape_hbm_bytes(shapes.get(o, "")) for o in operand_names)
        if op in (
            "copy-start",
            "async-start",
            "all-gather-start",
            "collective-permute-start",
        ):
            # These start ops' result tuples carry an ALIAS of the operand
            # alongside the real destination; subtracting the operand
            # footprint leaves exactly the destination write (0 for
            # HBM→VMEM prefetches, dest size for HBM→HBM copies).
            # all-gather-start and collective-permute-start return
            # (operand, result) tuples whose FIRST element aliases the
            # input — without the subtraction the operand is double-charged
            # as an HBM write on multi-chip HLOs, recreating the
            # "Σ attainable above measured" impossible-lower-bound failure.
            # all-reduce-start is NOT included: its result is the reduced
            # output itself, a real write with no alias element.
            out_b = max(0, out_b - in_b)
        fl = 0.0
        if op == "convolution":
            fl = conv_flops(shape_text, rest, shapes)
        elif op == "dot":
            fl = dot_flops(shape_text, rest, shapes)
        elif op == "fusion":
            mcall = re.search(r"calls=%?([\w.\-]+)", rest)
            if mcall:
                fl = fused_flops.get(mcall.group(1), 0.0)
        total_b = out_b + in_b
        row = {"op": op, "name": name, "bytes": total_b, "flops": fl}
        if peak_tflops and peak_gbps:
            t_flops = fl / (peak_tflops * 1e12)
            t_bytes = total_b / (peak_gbps * 1e9)
            row["attainable_ms"] = max(t_flops, t_bytes) * 1e3
            row["bound"] = "flops" if t_flops >= t_bytes else "bytes"
        rows.append(row)
    return rows


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18")
    ap.add_argument("--batch", type=int, default=2048, help="per chip")
    ap.add_argument("--image", type=int, default=128)
    ap.add_argument("--top", type=int, default=20)
    ap.add_argument("--json", default="", help="write full rows to this path")
    ap.add_argument("--dump-hlo", default="",
                    help="write the optimized HLO text to this path (the "
                    "instruction names in the roofline rows index into it)")
    ap.add_argument("--measured-ms", type=float, default=0.0,
                    help="measured step ms (from bench_zoo) for the ceiling line")
    args = ap.parse_args()

    from bench_zoo import build_state_and_batch

    from mpi_pytorch_tpu.train.step import make_train_step
    from mpi_pytorch_tpu.utils.hardware import (
        peak_bf16_tflops,
        peak_hbm_gbps,
        step_flops,
    )

    mesh, state, batch, n_chips, _ = build_state_and_batch(
        args.model, args.batch, args.image
    )
    step = make_train_step(jnp.bfloat16)
    # Score the exact compile that runs: MPT_COMPILER_OPTIONS (same JSON
    # contract as bench.py/bench_zoo.py) reaches this compile too, so the
    # roofline of e.g. the shipped vmem-64M configuration is the roofline
    # OF that configuration (more S(1)-pinned buffers → fewer HBM bytes).
    env_options = os.environ.get("MPT_COMPILER_OPTIONS")
    options = json.loads(env_options) if env_options else {}
    compiled = step.lower(state, batch).compile(compiler_options=options or None)
    hlo = compiled.as_text()
    if args.dump_hlo:
        with open(args.dump_hlo, "w") as f:
            f.write(hlo)
        print(f"optimized HLO written: {args.dump_hlo}")
    dev = jax.devices()[0]
    peak_t, peak_b = peak_bf16_tflops(dev), peak_hbm_gbps(dev)

    rows = roofline(hlo, peak_t, peak_b)
    rows.sort(key=lambda r: r.get("attainable_ms", r["bytes"]), reverse=True)
    total_flops = step_flops(compiled)

    print(f"# roofline: {args.model} b={args.batch} img={args.image} "
          f"chip={dev.device_kind!r} peak={peak_t} TF/s {peak_b} GB/s")
    hdr = f"{'op':<14}{'bytes/MB':>10}{'GFLOP':>9}{'attain ms':>11}  bound"
    print(hdr)
    for r in rows[: args.top]:
        print(
            f"{r['op']:<14}{r['bytes'] / 1e6:>10.2f}{r['flops'] / 1e9:>9.2f}"
            f"{r.get('attainable_ms', float('nan')):>11.4f}  {r.get('bound', '?')}"
        )
    if peak_t and peak_b:
        lower_ms = sum(r["attainable_ms"] for r in rows)
        line = {
            "model": args.model,
            "sum_attainable_ms": round(lower_ms, 3),
            "hlo_flops": total_flops,
            "ceiling_mfu_pct": round(
                100.0 * total_flops / (peak_t * 1e12) / (lower_ms / 1e3), 1
            ) if lower_ms else None,
        }
        if args.measured_ms:
            line["measured_ms"] = args.measured_ms
            line["measured_vs_lower_bound"] = round(args.measured_ms / lower_ms, 2)
        print(json.dumps(line))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)
        print(f"rows written: {args.json}")


if __name__ == "__main__":
    main()
