"""Tests for the multi-model tenancy subsystem (mpi_pytorch_tpu/serve/zoo/,
ISSUE 14).

The acceptance surface: packing-plan invariants (an over-budget spec is
rejected loudly), per-tenant front-door admission (a flooding tenant is
rejected while the others keep serving), model-aware routing with the
cold-load spill, the model-labelled controller retune with
``compiles == 0``, cold-swap warm-probe gating, LRU eviction under the
packing budget, single-tenant flush discipline, the ``RemoteHost`` facts
generation invalidation satellite, schema-v10 shapes, and the
model/load_shape-keyed regression gate.

Fast tests drive fakes (no jax compute); one module-scoped REAL 2-tenant
fleet on the 8-device CPU mesh pins the end-to-end behavior (the
``_dryrun_zoo`` CI leg's in-process twin).
"""

import json
import os
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _images(n, size=32, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, 256, size=(size, size, 3)).astype(np.uint8)
        for _ in range(n)
    ]


# ------------------------------------------------------------- spec parsing


def test_parse_model_specs_syntax():
    from mpi_pytorch_tpu.serve.zoo import parse_model_specs

    specs = parse_model_specs(
        "hot=resnet18:admission=8,mobilenet_v2:cold,"
        "b=resnet18:precision=int8:buckets=1|8:ckpt=/ck"
    )
    by_name = {s.model: s for s in specs}
    assert set(by_name) == {"hot", "mobilenet_v2", "b"}
    assert by_name["hot"].arch == "resnet18"
    assert by_name["hot"].admission == 8
    assert by_name["mobilenet_v2"].cold
    assert by_name["b"].precision == "int8"
    assert by_name["b"].buckets == "1,8"
    assert by_name["b"].checkpoint_dir == "/ck"

    with pytest.raises(ValueError, match="duplicate"):
        parse_model_specs("resnet18,resnet18")
    with pytest.raises(ValueError, match="unsupported architecture"):
        parse_model_specs("not_a_model")
    with pytest.raises(ValueError, match="unknown spec key"):
        parse_model_specs("resnet18:bogus=1")
    with pytest.raises(ValueError, match="precision"):
        parse_model_specs("resnet18:precision=fp64")
    with pytest.raises(ValueError, match="zero tenants"):
        parse_model_specs(" , ")


def test_config_validates_zoo_knobs():
    from mpi_pytorch_tpu.config import Config

    Config(serve_models="resnet18,mobilenet_v2").validate_config()
    with pytest.raises(ValueError, match="cold"):
        Config(serve_models="resnet18:cold").validate_config()
    with pytest.raises(ValueError, match="duplicate"):
        Config(serve_models="resnet18,resnet18").validate_config()
    with pytest.raises(ValueError, match="serve_pack_budget_mb"):
        Config(serve_pack_budget_mb=64.0).validate_config()
    with pytest.raises(ValueError):
        Config(serve_models="resnet18", serve_pack_budget_mb=-1).validate_config()


# ------------------------------------------------------------- packing plan


def _registry_with_estimates(cfg, estimates_mb):
    """A real ModelRegistry whose byte estimates are injected (no
    eval_shape) — the planner logic under test, not the model zoo."""
    from mpi_pytorch_tpu.serve.zoo import ModelRegistry

    reg = ModelRegistry.from_config(cfg)
    mb = 1024 * 1024
    reg._estimates = {
        m: {
            "params_bytes": int(v * mb),
            "per_bucket_bytes": {1: 0},
            "total_bytes": int(v * mb),
        }
        for m, v in estimates_mb.items()
    }
    return reg


def test_packing_plan_rejects_single_over_budget_spec_loudly():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.zoo import PackingError

    cfg = Config(serve_models="a=resnet18,b=resnet18")
    reg = _registry_with_estimates(cfg, {"a": 100.0, "b": 10.0})
    with pytest.raises(PackingError) as ei:
        reg.plan_packing(["a", "b"], budget_bytes=50 * 1024 * 1024)
    # The loud rejection carries the plan's arithmetic.
    assert "alone exceeds" in str(ei.value)
    assert "100.0 MB" in str(ei.value)


def test_packing_plan_fits_explain_and_record():
    from mpi_pytorch_tpu.config import Config

    cfg = Config(serve_models="a=resnet18,b=resnet18")
    reg = _registry_with_estimates(cfg, {"a": 30.0, "b": 30.0})
    plan = reg.plan_packing(["a", "b"], budget_bytes=100 * 1024 * 1024)
    assert plan.fits and plan.total_bytes == 60 * 1024 * 1024
    assert "FITS" in plan.explain()
    rec = plan.to_record()
    assert rec["fits"] == 1 and rec["tenants"] == {"a": 30.0, "b": 30.0}
    # Two tenants that fit alone but not together: fits=False (the
    # eviction path's decision input), never a silent truncation.
    tight = reg.plan_packing(["a", "b"], budget_bytes=40 * 1024 * 1024)
    assert not tight.fits
    assert "OVER BUDGET" in tight.explain()
    # measured overrides the estimate where available
    measured = reg.plan_packing(
        ["a"], budget_bytes=None, measured={"a": 5 * 1024 * 1024}
    )
    assert measured.entries[0].total_bytes == 5 * 1024 * 1024
    assert measured.entries[0].measured


def test_tenant_budgets_explicit_and_equal_share():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.zoo import ModelRegistry

    cfg = Config(serve_models="hot=resnet18:admission=8,b=resnet18")
    reg = ModelRegistry.from_config(cfg)
    budgets = reg.tenant_budgets(100)
    assert budgets == {"hot": 8, "b": 50}


# ------------------------------------------------- cold-swap warm-probe gate


class _FakeExe:
    """BucketExecutables-shaped fake: scriptable compile counter so the
    warm-probe gate is testable in milliseconds."""

    def __init__(self, state_bytes=4, probe_compiles=0):
        self._state = np.zeros(max(1, state_bytes // 4), np.float32)
        self.buckets = (1,)
        self._image_hw = (4, 4)
        self.image_dtype = np.dtype(np.uint8)
        self.warm = False
        self.precision = "bf16"
        self._probe_compiles = probe_compiles
        self._compiles = 0

    def warmup(self):
        self.warm = True

    def rebaseline(self):
        self._compiles = 0

    def place(self, images, labels):
        return (images, labels)

    def __call__(self, bucket, batch):
        # Simulate a steady-state compile on execution when scripted.
        self._compiles += self._probe_compiles
        return np.zeros((bucket, 1), np.int32)

    def compiles_since_warmup(self):
        return self._compiles


def test_cold_swap_warm_probe_gates_activation():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.zoo import ZooExecutablePool
    from mpi_pytorch_tpu.serve.zoo.pool import ColdSwapError

    cfg = Config(serve_models="a=resnet18,b=resnet18")
    reg = _registry_with_estimates(cfg, {"a": 1.0, "b": 1.0})
    built = []

    def build_fn(tenant_cfg, mesh):
        bad = tenant_cfg.model_name == "resnet18" and len(built) == 1
        built.append(tenant_cfg.model_name)
        return {"bf16": _FakeExe(probe_compiles=1 if bad else 0)}

    pool = ZooExecutablePool(cfg, reg, mesh=object(), build_fn=build_fn)
    sets = pool.ensure("a")  # clean probe → activates
    assert pool.resident() == ("a",)
    assert sets["bf16"].warm
    # The second build compiles ON THE PROBE → the gate refuses to
    # activate it, and the pool stays without the tenant.
    with pytest.raises(ColdSwapError, match="warm probe"):
        pool.ensure("b")
    assert pool.resident() == ("a",)


def test_pool_refcounts_and_release():
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.zoo import ZooExecutablePool

    cfg = Config(serve_models="a=resnet18")
    reg = _registry_with_estimates(cfg, {"a": 1.0})
    pool = ZooExecutablePool(
        cfg, reg, mesh=object(),
        build_fn=lambda c, m: {"bf16": _FakeExe(state_bytes=2048)},
    )
    pool.ensure("a")
    pool.ensure("a")  # second host holds it too
    assert pool.measured_bytes() == {"a": 2048}
    pool.release("a")
    assert pool.resident() == ("a",)  # one ref left
    pool.release("a")
    assert pool.resident() == ()  # last ref dropped the sets
    # measured bytes stay cached for the next plan
    assert pool.measured_bytes() == {"a": 2048}


# --------------------------------------------- router: admission + routing


class _FakeZooHost:
    """Router-facing fake with the zoo surface: resident models,
    scriptable ensure_model, futures resolved by the test."""

    def __init__(self, name, models=(), queue_capacity=64):
        self.name = name
        self.index = int(name[1:])
        self._models = list(models)
        self.queue_capacity = queue_capacity
        self.submits = []  # (model, future)
        self.ensured = []

    def models(self):
        return tuple(self._models)

    def ensure_model(self, model):
        self.ensured.append(model)
        self._models.append(model)

    def submit(self, image, trace=None, model=None):
        fut = Future()
        self.submits.append((model, fut))
        return fut

    def snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def alive(self):
        return True

    def qsize(self):
        return 0

    def close(self, drain=True):
        pass

    def kill(self):
        pass


def _make_router(hosts, **kw):
    from mpi_pytorch_tpu.serve.fleet.router import FleetRouter

    kw.setdefault("probe_interval_s", 3600.0)  # no background probes
    return FleetRouter(hosts, **kw)


def test_per_tenant_admission_isolates_hot_tenant():
    from mpi_pytorch_tpu.serve import QueueFullError

    h0 = _FakeZooHost("h0", models=("a", "b"))
    router = _make_router([h0], tenant_budgets={"a": 2, "b": 4})
    try:
        futs = [router.submit(0, model="a") for _ in range(2)]
        # Tenant a's budget is exhausted — rejected AT THE FRONT DOOR,
        # and the typed error names the tenant.
        with pytest.raises(QueueFullError) as ei:
            router.submit(0, model="a")
        assert ei.value.model == "a"
        assert "tenant 'a'" in str(ei.value)
        # Tenant b keeps admitting through a's flood.
        fb = router.submit(0, model="b")
        assert router.rejections_by_model == {"a": 1, "b": 0}
        # Completion returns the tenant token: a admits again.
        h0.submits[0][1].set_result(np.zeros(3, np.int32))
        futs[0].result(timeout=5)
        deadline = time.monotonic() + 2
        while time.monotonic() < deadline:
            try:
                futs.append(router.submit(0, model="a"))
                break
            except QueueFullError:
                time.sleep(0.01)
        else:
            pytest.fail("tenant token never returned")
        for _, fut in h0.submits:
            if not fut.done():
                fut.set_result(np.zeros(3, np.int32))
        fb.result(timeout=5)
        stats = router.stats()
        assert stats["tenant_budgets"] == {"a": 2, "b": 4}
    finally:
        router.close()


def test_router_prefers_resident_host_and_cold_loads_on_spill():
    h0 = _FakeZooHost("h0", models=("a",))
    h1 = _FakeZooHost("h1", models=("b",))
    router = _make_router([h0, h1])
    try:
        router.submit(0, model="a")
        router.submit(0, model="b")
        assert [m for m, _ in h0.submits] == ["a"]
        assert [m for m, _ in h1.submits] == ["b"]
        # Tenant c is resident nowhere: the router cold-loads it on one
        # host (ensure_model) before the hand-over.
        router.submit(0, model="c")
        ensured = h0.ensured + h1.ensured
        assert ensured == ["c"]
        loaded = h0 if h0.ensured else h1
        assert loaded.submits[-1][0] == "c"
        for h in (h0, h1):
            for _, fut in h.submits:
                fut.set_result(np.zeros(3, np.int32))
    finally:
        router.close()


def test_router_routes_by_per_tenant_queue_depth():
    """Per-(host, model) scoring: a host whose TENANT queue is deep
    loses the tenant's traffic even when its host-level score ties."""
    h0 = _FakeZooHost("h0", models=("a",))
    h1 = _FakeZooHost("h1", models=("a",))
    router = _make_router([h0, h1])
    try:
        # Feed fresh snapshots by hand: equal host scores, but h0's
        # tenant-a queue is deep.
        snap_busy = {
            "counters": {}, "gauges": {"serve/queue_depth": 0},
            "histograms": {},
            "models": {"a": {"gauges": {"serve/queue_depth": 10}}},
        }
        snap_idle = {
            "counters": {}, "gauges": {"serve/queue_depth": 0},
            "histograms": {},
            "models": {"a": {"gauges": {"serve/queue_depth": 0}}},
        }
        router._score_from_snapshot(h0, snap_busy)
        router._score_from_snapshot(h1, snap_idle)
        router.submit(0, model="a")
        assert len(h1.submits) == 1 and not h0.submits
        h1.submits[0][1].set_result(np.zeros(3, np.int32))
    finally:
        router.close()


def test_unknown_model_is_request_shaped_never_a_host_strike():
    """A typo'd model name must propagate to the caller as
    UnknownModelError — NOT count as dispatch failures that drain every
    healthy host fleet-wide (review finding on the cold-load spill)."""
    from mpi_pytorch_tpu.serve.batcher import UnknownModelError

    class _StrictHost(_FakeZooHost):
        def ensure_model(self, model):
            if model not in ("a", "b"):
                raise UnknownModelError(f"unknown model {model!r}")
            super().ensure_model(model)

    h0 = _StrictHost("h0", models=("a",))
    h1 = _StrictHost("h1", models=("b",))
    router = _make_router([h0, h1], fail_probes=1)
    try:
        for _ in range(5):  # well past fail_probes
            with pytest.raises(UnknownModelError):
                router.submit(0, model="typo")
        stats = router.stats()
        assert stats["dead"] == [], stats
        assert set(stats["hosts"]) == {"h0", "h1"}
        # A real tenant still routes fine afterwards.
        router.submit(0, model="a")
        h0.submits[0][1].set_result(np.zeros(3, np.int32))
    finally:
        router.close()


def test_failed_swap_in_rebaselines_resident_sets():
    """A swap-in that FAILS its warm probe must still re-baseline the
    already-resident sets: its cold compiles landed on their
    process-global counters, and a refused tenant must not leave
    phantom compiles on healthy ones (review finding)."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.zoo import ZooExecutablePool
    from mpi_pytorch_tpu.serve.zoo.pool import ColdSwapError

    cfg = Config(serve_models="a=resnet18,b=resnet18")
    reg = _registry_with_estimates(cfg, {"a": 1.0, "b": 1.0})
    exes = {}

    def build_fn(tenant_cfg, mesh):
        exe = _FakeExe(probe_compiles=1 if exes else 0)
        exes[tenant_cfg.model_name + str(len(exes))] = exe
        return {"bf16": exe}

    pool = ZooExecutablePool(cfg, reg, mesh=object(), build_fn=build_fn)
    a_exe = pool.ensure("a")["bf16"]
    # Simulate b's cold-load compiles landing on a's process-global
    # counter, then the swap-in failing its probe.
    a_exe._compiles = 3
    with pytest.raises(ColdSwapError):
        pool.ensure("b")
    assert a_exe.compiles_since_warmup() == 0, (
        "failed swap-in left phantom compiles on a resident set"
    )


# --------------------------------------------- controller: model labelling


class _FakeTenantUnit:
    def __init__(self, host_name, model, p99):
        self.host_name = host_name
        self.model = model
        self.name = f"{host_name}/{model}"
        self.max_wait_ms = 8.0
        self.buckets = (1, 4)
        self.active_buckets = (1, 4)
        self.precision = "bf16"
        self.precisions = ("bf16",)
        self.parity_top1 = None
        self._p99 = p99
        self._count = 10

    def snapshot(self):
        return {"histograms": {
            "serve/request_latency_ms": {
                "count": self._count, "sum": 1.0, "p99": self._p99,
            },
            "serve/fill_pct": {"count": 1, "sum": 80.0},
        }}

    def set_max_wait_ms(self, v):
        self.max_wait_ms = v

    def set_active_buckets(self, b):
        self.active_buckets = tuple(b)

    def set_precision(self, p):
        self.precision = p

    def compiles_after_warmup(self):
        return 0


class _FakeZooControllerHost:
    name = "h0"

    def __init__(self, units):
        self._units = units

    def tenants(self):
        return list(self._units)


class _ListWriter:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(dict(rec))


def test_controller_retunes_per_tenant_with_model_label():
    from mpi_pytorch_tpu.serve.fleet.controller import FleetController

    hot = _FakeTenantUnit("h0", "a", p99=50.0)  # breaches
    cold = _FakeTenantUnit("h0", "b", p99=1.0)  # deep headroom
    writer = _ListWriter()
    ctl = FleetController(
        lambda: [_FakeZooControllerHost([hot, cold])],
        target_p99_ms=10.0, metrics=writer,
    )
    retuned = ctl.tick()
    assert retuned == 1  # only the breaching tenant moved
    assert hot.max_wait_ms == 4.0  # halved
    assert cold.max_wait_ms == 8.0  # untouched — isolation
    recs = [r for r in writer.records if r.get("event") == "retune"]
    assert len(recs) == 1
    assert recs[0]["model"] == "a"
    assert recs[0]["host"] == "h0"
    assert recs[0]["compiles_after_warmup"] == 0
    from mpi_pytorch_tpu.obs.schema import validate_record

    recs[0]["ts"] = 1.0
    assert validate_record(recs[0]) == []


# -------------------------------------- RemoteHost facts generation satellite


class _FakeZooWireServer:
    """Duck-typed multi-tenant server behind the REAL wire stack
    (ServingHost + ObsHTTPServer): scriptable resident set + facts
    generation, no jax."""

    name = "h0"

    def __init__(self):
        self.resident = ["a", "b"]
        self.generation = 1
        self.submits = []

    def submit(self, image, model=None, trace=None):
        self.submits.append(model)
        fut = Future()
        fut.set_result(np.zeros(3, np.int32))
        return fut

    def ensure_model(self, model):
        if model not in self.resident:
            self.resident.append(model)
            self.generation += 1

    def evict_model(self, model):
        self.resident.remove(model)
        self.generation += 1

    def registry_snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {},
                "models": {m: {} for m in self.resident},
                "facts_generation": self.generation,
                "seq": 0, "start_ts": 123.0}

    def stats(self):
        return {"served": len(self.submits), "models": {}}

    def _healthz(self):
        return {
            "status": "ok", "queue_depth": 0, "compiles_after_warmup": 0,
            "served": 0, "rejected": 0, "buckets": [1, 4],
            "precision": "bf16", "queue_capacity": 64,
            "max_wait_ms": 2.0, "active_buckets": [1, 4],
            "precisions": ["bf16"], "parity_top1": None, "topk": 3,
            "host_index": 0, "pid": None, "time": time.time(),
            "start_ts": 123.0,
            "models": list(self.resident),
            "registered_models": ["a", "b", "c"],
            "facts_generation": self.generation,
        }

    def set_max_wait_ms(self, v):
        pass

    def close(self, drain=True):
        pass


def test_remote_facts_cache_invalidates_on_generation_change():
    """ISSUE 14 satellite: the RemoteHost facts cache (static /healthz
    facts + TTL) must refresh the moment the resident model set changes
    — the /metricsz probe carries the generation counter, so the router
    never dispatches a tenant to a host that just evicted it."""
    from mpi_pytorch_tpu.serve.fleet.remote import RemoteHost
    from mpi_pytorch_tpu.serve.host import ServingHost

    server = _FakeZooWireServer()
    wire = ServingHost(server, port=0)
    try:
        host = RemoteHost(
            f"http://127.0.0.1:{wire.port}", name="h0", index=0,
            facts_ttl_s=3600.0,  # TTL alone would NEVER refresh in-test
        )
        assert host.models() == ("a", "b")
        # The host evicts b; the facts cache is still warm (huge TTL).
        server.evict_model("b")
        assert host.models() == ("a", "b")  # stale — cache, by design
        # The probe loop's snapshot carries the new generation → the
        # facts cache invalidates → the next models() read is fresh.
        host.snapshot()
        assert host.models() == ("a",)
        # Wire submit carries the tenant; the zoo control ops cross too.
        host.submit(np.zeros((4, 4, 3), np.uint8), model="a").result(5)
        assert server.submits[-1] == "a"
        host.ensure_model("c")
        assert "c" in server.resident
        assert host.models() == ("a", "c")  # control invalidated facts
        host.close(drain=False)
    finally:
        wire.close(drain=False)


# ------------------------------------------------------------- schema v10


def test_schema_v10_shapes():
    from mpi_pytorch_tpu.obs.schema import SCHEMA_VERSION, validate_record

    assert SCHEMA_VERSION >= 10
    serve = {
        "kind": "serve", "ts": 1.0, "bucket": 4, "requests": 3,
        "queue_depth": 0, "fill_ratio": 0.75, "queue_wait_ms": 1.0,
        "device_ms": 2.0, "model": "resnet18",
    }
    assert validate_record(serve) == []
    route = {
        "kind": "route", "ts": 1.0, "host": "h0", "requests": 5,
        "models": {"resnet18": 3, "mobilenet_v2": 2},
    }
    assert validate_record(route) == []
    swap = {
        "kind": "fleet", "ts": 1.0, "event": "swap_in", "host": "h0",
        "model": "mobilenet_v2", "resident": ["mobilenet_v2", "resnet18"],
        "compiles_after_warmup": 0,
        "plan": {"budget_mb": 100.0, "total_mb": 52.0, "fits": 1,
                 "tenants": {"resnet18": 43.0, "mobilenet_v2": 9.0}},
    }
    assert validate_record(swap) == []
    evict = {
        "kind": "fleet", "ts": 1.0, "event": "evict", "host": "h0",
        "model": "resnet18", "resident": [], "detail": "lru",
    }
    assert validate_record(evict) == []
    alert = {
        "kind": "alert", "ts": 1.0, "rule": "p99", "severity": "warn",
        "model": "resnet18",
    }
    assert validate_record(alert) == []
    bench = {
        "kind": "serve_bench", "ts": 1.0, "mode": "open", "buckets": "1,4",
        "max_wait_ms": 2.0, "requests": 10, "p50_ms": 1.0, "p95_ms": 2.0,
        "p99_ms": 3.0, "images_per_sec": 50.0, "model": "resnet18",
        "load_shape": "hot:resnet18",
    }
    assert validate_record(bench) == []
    # Wrong types still rejected.
    assert validate_record(dict(serve, model=3))
    assert validate_record(dict(route, models=[1]))


def test_monitor_labels_stamp_alert_records():
    from mpi_pytorch_tpu.obs.metrics import MetricsRegistry
    from mpi_pytorch_tpu.obs.monitor import SLOMonitor, parse_rules

    registry = MetricsRegistry()
    registry.counter("serve/rejected").inc(100)
    writer = _ListWriter()
    mon = SLOMonitor(
        registry, parse_rules("serve/rejected > 1 name=rej"),
        metrics=writer, labels={"model": "resnet18"},
    )
    mon.evaluate()
    assert writer.records and writer.records[0]["model"] == "resnet18"


# -------------------------------------------------------- regression keying


def test_check_regression_keys_model_and_load_shape(tmp_path):
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_regression

    def row(model, load_shape, p99):
        return {
            "kind": "serve_bench", "ts": 1.0, "mode": "open",
            "buckets": "1,4", "max_wait_ms": 2.0, "requests": 10,
            "p50_ms": 1.0, "p95_ms": 2.0, "p99_ms": p99,
            "images_per_sec": 100.0, "model": model,
            "load_shape": load_shape,
        }

    base = tmp_path / "base.json"
    new = tmp_path / "new.json"
    # Baseline: tenant a fast. New: tenant b slow at the SAME sweep
    # point — a DIFFERENT trend line, never compared.
    base.write_text(json.dumps(row("a", "uniform", 10.0)) + "\n")
    new.write_text(json.dumps(row("b", "uniform", 100.0)) + "\n")
    assert check_regression.check_serve(str(new), str(base), 10.0) == []
    # Same tenant, different load shape: also never compared.
    new.write_text(json.dumps(row("a", "hot:a", 100.0)) + "\n")
    assert check_regression.check_serve(str(new), str(base), 10.0) == []
    # Same tenant, same shape, regressed p99: caught.
    new.write_text(json.dumps(row("a", "uniform", 100.0)) + "\n")
    violations = check_regression.check_serve(str(new), str(base), 10.0)
    assert len(violations) == 1 and "p99" in violations[0]


# ------------------------------------------------ real 2-tenant fleet (jax)


@pytest.fixture(scope="module")
def zoo_fleet(tmp_path_factory):
    """The module's one REAL fleet: 2 hosts × 2 resnet18-arch tenants
    (one cold) on the CPU mesh — every expensive end-to-end assertion
    shares its build."""
    from mpi_pytorch_tpu.config import Config
    from mpi_pytorch_tpu.serve.fleet import FleetServer

    tmp = tmp_path_factory.mktemp("zoo_fleet")
    cfg = Config(
        model_name="resnet18", num_classes=16, width=32, height=32,
        synthetic_data=True, compute_dtype="float32",
        serve_buckets="1,4", serve_max_wait_ms=2.0, serve_topk=3,
        serve_queue_depth=64, loader_workers=4,
        serve_fleet_hosts=2, serve_probe_interval_ms=50.0,
        serve_models="hot=resnet18:admission=4,b=resnet18:cold",
        metrics_file=str(tmp / "metrics.jsonl"),
        log_file="", eval_log_file="",
    )
    cfg.validate_config()
    fleet = FleetServer(cfg, load_checkpoint=False)
    yield fleet, cfg
    fleet.close()


def test_zoo_fleet_end_to_end(zoo_fleet):
    """The _dryrun_zoo twin: cold swap-in via the router, per-tenant
    admission isolation under a hot-tenant flood, single-tenant flushes,
    zero steady-state compiles, schema-clean v10 stream."""
    from mpi_pytorch_tpu.serve import QueueFullError

    fleet, cfg = zoo_fleet
    images = _images(8)

    # --- cold swap-in: tenant b is resident nowhere; the first request
    # spills to a cold-load (ensure_model) and still answers.
    preds = fleet.submit(images[0], model="b").result(timeout=120)
    assert preds.shape == (3,)
    resident = [set(h.models()) for h in fleet.router.active_hosts()]
    assert any("b" in r for r in resident)

    # --- hot-tenant flood: admission=4 binds at the front door; the
    # cold tenant keeps serving with rejected == 0.
    futs, rejected = [], 0
    for i in range(40):
        try:
            futs.append(fleet.submit(images[i % 8], model="hot"))
        except QueueFullError as e:
            assert e.model == "hot"
            rejected += 1
    for i in range(4):
        futs.append(fleet.submit(images[i], model="b"))
    for f in futs:
        f.result(timeout=120)
    assert rejected > 0
    assert fleet.router.rejections_by_model["b"] == 0
    ts = fleet.tenant_stats()
    assert ts["b"]["rejected"] == 0 and ts["b"]["front_door_rejections"] == 0
    assert ts["hot"]["front_door_rejections"] == rejected
    assert ts["b"]["served"] >= 5

    # --- zero steady-state compiles across every tenant set, through
    # the swap-in and the flood.
    assert fleet.stats()["compiles_after_warmup"] == 0


def test_zoo_fleet_controller_retunes_tenant_with_model_label(zoo_fleet):
    from mpi_pytorch_tpu.serve.fleet.controller import FleetController

    fleet, cfg = zoo_fleet
    writer = _ListWriter()
    # An impossible target: every tenant with observations breaches →
    # the retune halves its wait, per tenant, with compiles == 0.
    ctl = FleetController(
        fleet.router.active_hosts, target_p99_ms=0.001, metrics=writer,
    )
    retuned = ctl.tick()
    assert retuned >= 1
    recs = [r for r in writer.records if r.get("event") == "retune"]
    assert recs and all(r["compiles_after_warmup"] == 0 for r in recs)
    assert all(r.get("model") in ("hot", "b") for r in recs)


def test_zoo_fleet_single_tenant_flushes_and_stream(zoo_fleet):
    """Every serve record names exactly one tenant (flushes are
    single-tenant by construction), route windows carry per-tenant
    counts, the swap-in record carries its packing plan, and the whole
    stream validates as schema v10."""
    from mpi_pytorch_tpu.obs.schema import load_records, validate_jsonl

    fleet, cfg = zoo_fleet
    # Flush the router's open windows so route records land.
    fleet.router._write_route_records(force=True)
    assert validate_jsonl(cfg.metrics_file) == []
    recs = load_records(cfg.metrics_file)
    serves = [r for r in recs if r["kind"] == "serve"]
    assert serves
    assert all(r.get("model") in ("hot", "b") for r in serves)
    swaps = [
        r for r in recs
        if r["kind"] == "fleet" and r.get("event") == "swap_in"
    ]
    assert len(swaps) >= 1
    assert swaps[0]["model"] == "b"
    assert swaps[0]["compiles_after_warmup"] == 0
    assert "b" in swaps[0]["resident"]
    assert swaps[0]["plan"]["fits"] == 1
    routes = [r for r in recs if r["kind"] == "route" and r.get("models")]
    assert routes, "no route window carried per-tenant counts"


def test_zoo_lru_eviction_under_budget(zoo_fleet):
    """Shrinking the packing budget below the resident set forces the
    next swap-in to evict the LRU tenant — and the facts generation
    moves so routing facts stay coherent."""
    fleet, cfg = zoo_fleet
    # The cold-load spill picked ONE host for tenant b — use that one.
    host = next(
        h for h in fleet.router.active_hosts() if "b" in h.models()
    )
    server = host.server
    assert set(server.models()) == {"hot", "b"}
    gen0 = server.facts_generation
    # Touch "b" so "hot" is the LRU victim, then make the budget only
    # fit one tenant + the incoming one.
    server.submit(_images(1)[0], model="b").result(timeout=60)
    measured = server.pool.measured_bytes()
    one_tenant = max(measured.values())
    server._budget_bytes = int(one_tenant * 2.2)
    # Evict + re-ensure: evict hot manually is NOT the point — ask for
    # an eviction via the budget by re-activating a previously evicted
    # tenant. Simplest deterministic route: evict b, then re-ensure b
    # under the tightened budget — hot (LRU) must be evicted to fit.
    server.evict_model("b")
    server._last_used["hot"] = 0.0  # pin hot as least-recently-used
    server.ensure_model("b")
    after = set(server.models())
    assert "b" in after
    assert server.facts_generation > gen0
    # restore for other tests
    server._budget_bytes = None
    server.ensure_model("hot")
    assert set(server.models()) == {"hot", "b"}
